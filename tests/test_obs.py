"""The observability subsystem: metrics registry + exposition format,
health endpoints, scheduler instrument recording, span profiling, the
trace analysis backend, and the daemon e2e (``--metrics_port`` +
``--trace_profile`` against the fake apiserver)."""

import json
import threading
import urllib.error
import urllib.request

import pytest

from poseidon_tpu.bridge import SchedulerBridge, SchedulerStats
from poseidon_tpu.cluster import Task
from poseidon_tpu.obs import (
    HealthState,
    MetricsRegistry,
    ObsServer,
    SchedulerMetrics,
)
from poseidon_tpu.obs.metrics import (
    STORM_RESYNCS,
    _bounded_why,
    resync_reason_label,
)
from poseidon_tpu.obs.report import analyze_trace, render_report
from poseidon_tpu.obs.spans import chrome_trace, round_span_tree
from poseidon_tpu.synth import make_synthetic_cluster
from poseidon_tpu.trace import TraceGenerator

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


def _get(port, path):
    try:
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5.0
        )
        return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class TestRegistry:
    def test_counter_gauge_render(self):
        reg = MetricsRegistry()
        c = reg.counter("jobs_total", "help text")
        g = reg.gauge("depth")
        c.inc()
        c.inc(2, queue="fast")
        g.set(7.5)
        text = reg.render()
        assert "# HELP jobs_total help text" in text
        assert "# TYPE jobs_total counter" in text
        assert "jobs_total 1" in text
        assert 'jobs_total{queue="fast"} 2' in text
        assert "# TYPE depth gauge" in text
        assert "depth 7.5" in text

    def test_histogram_cumulative_buckets(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_ms", buckets=(1.0, 10.0, 100.0))
        for v in (0.5, 5.0, 5.0, 50.0, 5000.0):
            h.observe(v)
        text = reg.render()
        assert 'lat_ms_bucket{le="1"} 1' in text
        assert 'lat_ms_bucket{le="10"} 3' in text
        assert 'lat_ms_bucket{le="100"} 4' in text
        assert 'lat_ms_bucket{le="+Inf"} 5' in text
        assert "lat_ms_sum 5060.5" in text
        assert "lat_ms_count 5" in text

    def test_registration_idempotent_kind_mismatch_raises(self):
        reg = MetricsRegistry()
        c = reg.counter("x_total")
        assert reg.counter("x_total") is c
        with pytest.raises(ValueError):
            reg.gauge("x_total")

    def test_concurrent_recording_is_consistent(self):
        reg = MetricsRegistry()
        c = reg.counter("n_total")

        def work():
            for _ in range(1000):
                c.inc()

        threads = [threading.Thread(target=work) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert "n_total 4000" in reg.render()


class TestSchedulerMetrics:
    def _stats(self, **kw):
        s = SchedulerStats(round_num=1)
        s.backend = kw.pop("backend", "dense_auction")
        s.lane = kw.pop("lane", "watch")
        s.build_mode = kw.pop("build_mode", "delta")
        s.total_ms = kw.pop("total_ms", 5.0)
        for k, v in kw.items():
            setattr(s, k, v)
        return s

    def test_record_round_families(self):
        m = SchedulerMetrics(MetricsRegistry())
        m.record_round(self._stats(
            pods_total=10, pods_pending=3, deltas_place=3,
            deltas_migrate=1, bind_failures=2,
        ))
        text = m.registry.render()
        assert ('poseidon_rounds_total{backend="dense",lane="watch"} 1'
                in text)
        assert ('poseidon_round_latency_ms_bucket{build_mode="delta",'
                'lane="watch",le="5"} 1' in text)
        assert 'poseidon_deltas_total{kind="migrate"} 1' in text
        assert "poseidon_bind_failures_total 2" in text
        assert 'poseidon_pods{state="total"} 10' in text

    def test_degraded_gauge_sets_and_clears(self):
        m = SchedulerMetrics(MetricsRegistry())
        m.record_round(self._stats(backend="oracle:memory-envelope"))
        assert ('poseidon_degraded{why="memory-envelope"} 1'
                in m.registry.render())
        # an empty (no-solve) round carries no evidence either way
        m.record_round(self._stats(backend="", build_mode=""))
        assert ('poseidon_degraded{why="memory-envelope"} 1'
                in m.registry.render())
        # ANY non-degraded solve clears the flag — deliberate oracle
        # routing included (it is dispatch, not degradation)
        m.record_round(self._stats(backend="oracle:small-instance"))
        assert ('poseidon_degraded{why="memory-envelope"} 0'
                in m.registry.render())
        m.record_round(self._stats(backend="oracle:cost-domain"))
        m.record_round(self._stats(backend="dense_auction"))
        assert ('poseidon_degraded{why="cost-domain"} 0'
                in m.registry.render())

    def test_resync_storm_gauge(self):
        m = SchedulerMetrics(MetricsRegistry())
        m.record_round(self._stats(watch_resyncs=0))
        assert "poseidon_watch_resync_storm 0" in m.registry.render()
        m.record_round(self._stats(watch_resyncs=STORM_RESYNCS))
        assert "poseidon_watch_resync_storm 1" in m.registry.render()

    def test_reason_labels_are_bounded(self):
        assert resync_reason_label("rv 7 expired (HTTP 410)") == "gone"
        assert resync_reason_label(
            "pods: no stream activity for 30s (--watch_max_lag)"
        ) == "stale"
        assert resync_reason_label(
            "pods: unparseable ADDED event: KeyError('uid')"
        ) == "decode"
        assert _bounded_why("4 arrivals > --express_max_batch 2") \
            == "batch-size"
        assert _bounded_why("unconfirmed placements") == "unconfirmed"

    def test_empty_round_keeps_counters_out_of_latency(self):
        """An idle cluster's empty rounds flush window counters but
        must not feed the latency histogram or clobber the last real
        round's cost/phase gauges."""
        m = SchedulerMetrics(MetricsRegistry())
        m.record_round(self._stats(cost=42, solve_ms=3.0))
        m.record_round(self._stats(
            backend="", build_mode="", total_ms=0.001, cost=0,
            solve_ms=0.0, bind_failures=1,
        ))
        text = m.registry.render()
        assert "poseidon_round_latency_ms_count" in text
        assert ('poseidon_round_latency_ms_count{build_mode="delta",'
                'lane="watch"} 1' in text)
        assert 'build_mode=""' not in text  # no empty-round sample
        assert "poseidon_round_cost 42" in text
        assert 'poseidon_round_phase_ms{phase="solve"} 3' in text
        assert "poseidon_bind_failures_total 1" in text  # counters flow
        assert ('poseidon_rounds_total{backend="empty",lane="watch"} 1'
                in text)

    def test_express_batch_recording(self):
        m = SchedulerMetrics(MetricsRegistry())
        m.record_express_batch([2.5, 0.7, 1.1])
        m.record_express_batch([])  # retire-only batch: no placements
        text = m.registry.render()
        assert "poseidon_express_batches_total 2" in text
        assert "poseidon_express_places_total 3" in text
        assert "poseidon_express_e2b_ms_count 3" in text


class TestServer:
    def test_endpoints_and_readyz_latch(self):
        reg = MetricsRegistry()
        reg.counter("poseidon_rounds_total").inc()
        health = HealthState()
        with ObsServer(reg, health, port=0, host="127.0.0.1") as srv:
            assert _get(srv.port, "/healthz")[0] == 200
            code, body = _get(srv.port, "/readyz")
            assert code == 503
            assert "seed LIST" in body and "scheduling round" in body
            # a proven-empty round counts (an idle cluster is the
            # steady state of an operational scheduler) — but only
            # once seeded
            health.mark_round("")
            assert _get(srv.port, "/readyz")[0] == 503
            health.mark_seeded()
            health.mark_round("")
            assert _get(srv.port, "/readyz")[0] == 200
            code, body = _get(srv.port, "/metrics")
            assert code == 200
            assert "poseidon_rounds_total 1" in body
            assert _get(srv.port, "/nope")[0] == 404

    def test_ready_gauge_flips_with_the_latch(self):
        """HealthState owns the poseidon_ready gauge: both flip under
        one lock, so a scraper that saw /readyz 200 can never read the
        gauge at 0."""
        reg = MetricsRegistry()
        metrics = SchedulerMetrics(reg)
        health = HealthState(ready_gauge=metrics.ready)
        assert "poseidon_ready 0" in reg.render()
        health.mark_seeded()
        assert "poseidon_ready 0" in reg.render()
        # a proven-empty round after seeding flips both together
        health.mark_round("")
        assert health.ready
        assert "poseidon_ready 1" in reg.render()

    def test_build_info_gauge_and_healthz_echo(self):
        """poseidon_build_info scrapes with the deploy-identity labels
        and /healthz echoes the same dict as JSON."""
        import jax

        from poseidon_tpu.obs import build_info

        import poseidon_tpu

        reg = MetricsRegistry()
        metrics = SchedulerMetrics(reg)
        info = build_info(mesh_width=4)
        assert info["version"] == poseidon_tpu.__version__
        assert info["jax"] == jax.__version__
        metrics.set_build_info(info)
        text = reg.render()
        assert "# TYPE poseidon_build_info gauge" in text
        line = next(
            ln for ln in text.splitlines()
            if ln.startswith("poseidon_build_info{")
        )
        assert f'version="{poseidon_tpu.__version__}"' in line
        assert 'mesh_width="4"' in line
        assert line.endswith(" 1")
        with ObsServer(reg, HealthState(), port=0, host="127.0.0.1",
                       build=info) as srv:
            code, body = _get(srv.port, "/healthz")
            assert code == 200
            doc = json.loads(body)
            assert doc["status"] == "ok"
            assert doc["build"]["jax"] == jax.__version__
            assert doc["build"]["backend"] == info["backend"]
            # /metrics carries the family alongside
            assert "poseidon_build_info" in _get(
                srv.port, "/metrics"
            )[1]

    def test_scrape_concurrent_with_recording(self):
        reg = MetricsRegistry()
        c = reg.counter("poseidon_rounds_total")
        with ObsServer(reg, HealthState(), port=0,
                       host="127.0.0.1") as srv:
            stop = threading.Event()

            def record():
                while not stop.is_set():
                    c.inc()

            t = threading.Thread(target=record, daemon=True)
            t.start()
            try:
                for _ in range(20):
                    code, body = _get(srv.port, "/metrics")
                    assert code == 200
                    assert "poseidon_rounds_total" in body
            finally:
                stop.set()
                t.join(timeout=2.0)


class TestBridgeIntegration:
    def _run_rounds(self, *, profile=False, metrics=None, rounds=2):
        cluster = make_synthetic_cluster(
            20, 60, seed=5, prefs_per_task=2
        )
        trace = TraceGenerator()
        bridge = SchedulerBridge(
            cost_model="quincy", small_to_oracle=False, trace=trace,
            metrics=metrics, profile_spans=profile,
        )
        bridge.lane = "poll"
        bridge.observe_nodes(list(cluster.machines))
        bridge.observe_pods(list(cluster.tasks))
        for _ in range(rounds):
            res = bridge.run_scheduler()
            for uid, m in res.bindings.items():
                bridge.confirm_binding(uid, m)
        return bridge, trace, res

    def test_round_metrics_from_live_bridge(self):
        m = SchedulerMetrics(MetricsRegistry())
        bridge, _trace, res = self._run_rounds(metrics=m)
        assert res.stats.lane == "poll"
        text = m.registry.render()
        assert ('poseidon_rounds_total{backend="dense",lane="poll"} 2'
                in text)
        assert "poseidon_round_latency_ms_count" in text
        assert 'poseidon_solver_fetches_total{lane="round"} 2' in text
        assert "poseidon_solver_warm 1" in text

    def test_span_tree_emitted_per_round(self):
        bridge, trace, _res = self._run_rounds(profile=True)
        spans = [e for e in trace.events if e.event == "SPAN"]
        assert len(spans) == 2
        tree = spans[-1].detail
        assert tree["name"] == "round" and tree["lane"] == "poll"
        names = [c["name"] for c in tree["children"]]
        for phase in ("observe", "build", "dispatch", "solve-wait",
                      "actuate", "device-solve"):
            assert phase in names
        # sequential reconstruction: children tile the host track
        host = [c for c in tree["children"] if "track" not in c]
        for prev, nxt in zip(host, host[1:]):
            assert nxt["off_ms"] == pytest.approx(
                prev["off_ms"] + prev["dur_ms"], abs=0.01
            )

    def test_no_spans_without_flag(self):
        bridge, trace, _res = self._run_rounds(profile=False)
        assert not [e for e in trace.events if e.event == "SPAN"]

    def test_express_place_carries_e2b_detail(self):
        m = SchedulerMetrics(MetricsRegistry())
        cluster = make_synthetic_cluster(
            20, 90, seed=3, prefs_per_task=2
        )
        trace = TraceGenerator()
        bridge = SchedulerBridge(
            cost_model="quincy", small_to_oracle=False,
            express_lane=True, trace=trace, metrics=m,
            profile_spans=True,
        )
        bridge.observe_nodes(list(cluster.machines))
        bridge.observe_pods(list(cluster.tasks))
        res = bridge.run_scheduler()
        for uid, mach in res.bindings.items():
            bridge.confirm_binding(uid, mach)
        pod = Task(uid="xp-0", cpu_request=0.1, memory_request_kb=64,
                   data_prefs={cluster.machines[0].name: 400})
        r = bridge.express_batch([("ADDED", pod)])
        assert r is not None and r.bindings
        places = [e for e in trace.events if e.event == "EXPRESS_PLACE"]
        assert places and places[0].detail["e2b_ms"] > 0
        spans = [e for e in trace.events if e.event == "SPAN"
                 and e.detail.get("lane") == "express"]
        assert spans
        children = spans[0].detail["children"]
        names = [c["name"] for c in children]
        # the work phases tile the END of the e2b window; any
        # event-receipt wait renders as a leading e2b-wait span
        assert names[-3:] == ["prep", "upload", "solve"]
        assert names[:-3] in ([], ["e2b-wait"])
        root_dur = spans[0].detail["dur_ms"]
        last = children[-1]
        assert last["off_ms"] + last["dur_ms"] == pytest.approx(
            root_dur, abs=0.01
        )
        text = m.registry.render()
        assert "poseidon_express_batches_total 1" in text
        assert "poseidon_express_e2b_ms_count 1" in text
        assert 'poseidon_solver_fetches_total{lane="express"} 1' in text


class TestReportAndChrome:
    def _trace_file(self, tmp_path, profile=True):
        path = tmp_path / "trace.jsonl"
        cluster = make_synthetic_cluster(
            20, 60, seed=5, prefs_per_task=2
        )
        with open(path, "w") as fh:
            trace = TraceGenerator(sink=fh)
            bridge = SchedulerBridge(
                cost_model="quincy", small_to_oracle=False,
                trace=trace, profile_spans=profile,
            )
            bridge.lane = "watch+pipelined"
            bridge.observe_nodes(list(cluster.machines))
            bridge.observe_pods(list(cluster.tasks))
            for _ in range(2):
                res = bridge.run_scheduler()
                for uid, m in res.bindings.items():
                    bridge.confirm_binding(uid, m)
            trace.flush()
        return str(path)

    def test_analyze_trace(self, tmp_path):
        data = analyze_trace(self._trace_file(tmp_path))
        assert data["rounds"] == 2
        key = "watch+pipelined/full"
        assert key in data["round_latency_ms"]
        assert data["round_latency_ms"][key]["n"] >= 1
        assert data["backend_latency_ms"]["dense"]["p50"] > 0
        assert data["churn"]["totals"]["SCHEDULE"] > 0
        assert data["span_phase_p50_ms"]  # spans were on
        text = render_report(data)
        assert "round latency" in text and "placement churn" in text

    def test_cli_report_and_chrome(self, tmp_path, capsys):
        from poseidon_tpu.trace import main as trace_main

        path = self._trace_file(tmp_path)
        assert trace_main(["report", path]) == 0
        out = capsys.readouterr().out
        assert "poseidon-tpu trace report" in out
        assert trace_main(["report", path, "--json"]) == 0
        json.loads(capsys.readouterr().out)
        out_path = str(tmp_path / "t.chrome.json")
        assert trace_main(["chrome", path, "-o", out_path]) == 0
        doc = json.load(open(out_path))
        evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert evs and all("ts" in e and "dur" in e for e in evs)
        tids = {e["tid"] for e in evs}
        assert "device" in tids  # the device track stacks separately

    def test_chrome_trace_skips_non_spans(self):
        from poseidon_tpu.trace import TraceEvent

        doc = chrome_trace([
            TraceEvent(timestamp_us=1000, event="SUBMIT", task="p"),
            TraceEvent(
                timestamp_us=9000, event="SPAN",
                detail={"name": "round", "lane": "poll", "dur_ms": 2.0,
                        "children": [{"name": "build", "off_ms": 0.0,
                                      "dur_ms": 2.0}]},
            ),
        ])
        xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert len(xs) == 2  # root + one child, SUBMIT skipped
        assert xs[0]["ts"] == pytest.approx(7000.0)

    def test_empty_rounds_still_carry_window_counters(self, tmp_path):
        """The bridge flushes the window's express/bind-failure
        counters into empty rounds (an express window that bound
        everything ends in one) — the report must count them, not
        skip them with the latency grouping."""
        from poseidon_tpu.trace import TraceEvent

        path = tmp_path / "empty.jsonl"
        with open(path, "w") as fh:
            for ev in (
                TraceEvent(
                    timestamp_us=1, event="ROUND", round_num=1,
                    detail={"backend": "dense_auction", "lane": "express",
                            "build_mode": "delta", "total_ms": 5.0},
                ),
                TraceEvent(
                    timestamp_us=2, event="ROUND", round_num=2,
                    detail={"backend": "", "express_batches": 3,
                            "express_places": 4, "bind_failures": 1,
                            "deltas_deferred": 2},
                ),
            ):
                fh.write(json.dumps(ev.__dict__) + "\n")
        data = analyze_trace(str(path))
        assert data["express"]["batches"] == 3
        assert data["express"]["places"] == 4
        assert data["churn"]["bind_failures"] == 1
        assert data["churn"]["deltas_deferred"] == 2
        # the empty round still does not contribute a latency sample
        assert data["nonempty_rounds"] == 1

    def test_round_span_tree_nested_fetch_wait(self):
        s = SchedulerStats(round_num=3)
        s.observe_ms, s.build_ms, s.dispatch_ms = 1.0, 2.0, 0.5
        s.overlap_ms, s.fetch_wait_ms, s.solve_ms = 4.0, 1.5, 6.0
        tree = round_span_tree(s, join_ms=2.0, actuate_ms=0.25)
        wait = next(c for c in tree["children"]
                    if c["name"] == "solve-wait")
        assert wait["children"][0]["name"] == "fetch-wait"
        assert tree["dur_ms"] == pytest.approx(
            1.0 + 2.0 + 0.5 + 4.0 + 2.0 + 0.25
        )


class TestTenantReport:
    def _two_tenant_trace(self, tmp_path):
        """A fake-serve-shaped trace: two tenant sessions writing into
        ONE sink, each generator stamped with its tenant id (exactly
        what service.add_tenant does)."""
        path = tmp_path / "serve.jsonl"
        with open(path, "w") as fh:
            for tid, n_rounds, total in (
                ("tenant-0", 3, 5.0), ("tenant-1", 2, 50.0),
            ):
                gen = TraceGenerator(sink=fh, tenant=tid)
                for r in range(1, n_rounds + 1):
                    gen.emit(
                        "SCHEDULE", task=f"{tid}-pod-{r}",
                        machine=f"{tid}-n0", round_num=r,
                    )
                    gen.emit("ROUND", round_num=r, detail={
                        "backend": "dense_auction",
                        "lane": "service", "build_mode": "delta",
                        "total_ms": total,
                    })
                gen.flush()
        return str(path)

    def test_service_sessions_stamp_tenant(self):
        from poseidon_tpu.service.service import SchedulingService

        svc = SchedulingService()
        s = svc.add_tenant("acme")
        assert s.trace.tenant == "acme"
        s.bridge.trace.emit("ROUND", round_num=1)
        assert s.trace.events[-1].tenant == "acme"

    def test_tenant_filter_isolates_sessions(self, tmp_path):
        path = self._two_tenant_trace(tmp_path)
        whole = analyze_trace(path)
        t0 = analyze_trace(path, tenant="tenant-0")
        t1 = analyze_trace(path, tenant="tenant-1")
        assert whole["rounds"] == 5
        assert t0["rounds"] == 3 and t1["rounds"] == 2
        assert t0["churn"]["totals"]["SCHEDULE"] == 3
        assert t1["churn"]["totals"]["SCHEDULE"] == 2
        # latency percentiles come from ONLY the tenant's own rounds
        assert t0["round_latency_ms"]["service/delta"]["p50"] == 5.0
        assert t1["round_latency_ms"]["service/delta"]["p50"] == 50.0
        assert analyze_trace(path, tenant="ghost")["rounds"] == 0

    def test_report_cli_tenant_flag(self, tmp_path, capsys):
        from poseidon_tpu.trace import main as trace_main

        path = self._two_tenant_trace(tmp_path)
        rc = trace_main(["report", path, "--tenant", "tenant-1"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "tenant: tenant-1" in out
        assert "rounds: 2" in out


class TestZeroRecompileUnderDrain:
    def test_draining_pool_stays_zero_recompile(self):
        """Regression for the three recompile sources bench config 10
        flushed out: a pending pool that DRAINS across padding-bucket
        boundaries (cost-input shapes), packs its free seats (the
        ``smax`` static), and narrows its pref width (the ``n_prefs``
        static) must stay at zero steady-state recompiles — the
        solver's grow-only floors now cover all three axes, not just
        the topology padding."""
        from poseidon_tpu.guards import CompileCounter

        # oversubscribed on purpose: 160 seats, 224 pods — a standing
        # unscheduled pool of ~64 that the churn below drains a few
        # pods per round, so the pending count crosses padding-bucket
        # boundaries INSIDE the counted window (pre-fix, each crossing
        # recompiled the fused chain)
        cluster = make_synthetic_cluster(
            16, 224, seed=7, prefs_per_task=2
        )
        bridge = SchedulerBridge(
            cost_model="quincy", small_to_oracle=False,
        )
        bridge.observe_nodes(list(cluster.machines))
        bridge.observe_pods(list(cluster.tasks))
        res = bridge.run_scheduler()
        for uid, m in res.bindings.items():
            bridge.confirm_binding(uid, m)
        running = list(res.bindings)
        seq = 0

        def churn_round():
            # complete 6 running pods, arrive 2 single-pref pods: the
            # standing pool drains ~4/round (shrinking cost-input
            # shapes), freed seats churn (the smax static), and the
            # arrival mix narrows the pref width — the three pre-fix
            # recompile triggers
            nonlocal seq
            freed = ""
            for _ in range(6):
                done = running.pop(0)
                freed = bridge.pod_to_machine[done]
                bridge.observe_pod_event(
                    "DELETED", bridge.tasks[done]
                )
            for _ in range(2):
                bridge.observe_pod_event("ADDED", Task(
                    uid=f"dr-{seq}", cpu_request=0.1,
                    memory_request_kb=64, data_prefs={freed: 400},
                ))
                seq += 1
            r = bridge.run_scheduler()
            for uid, m in r.bindings.items():
                bridge.confirm_binding(uid, m)
                running.append(uid)

        for _ in range(2):  # warm both chain variants
            churn_round()
        counter = CompileCounter()
        with counter:
            for _ in range(10):
                churn_round()
        if not counter.supported:
            pytest.skip("jax.monitoring not available")
        assert counter.count == 0, (
            f"{counter.count} recompile(s) during a draining-pool "
            f"steady state"
        )


class TestDaemonE2E:
    def test_metrics_endpoint_live_daemon(self, tmp_path):
        """The acceptance scrape: a live fake-apiserver run exposes the
        required metric families, /readyz flips only after the first
        certified round, and the trace carries SPAN events."""
        import socket

        from poseidon_tpu.apiclient import FakeApiServer
        from poseidon_tpu.cli import parse_args, run_loop

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        trace_path = tmp_path / "daemon-trace.jsonl"
        seen = {}

        def scrape():
            # poll /readyz until it flips, then scrape /metrics while
            # the daemon is still serving
            import time as _time

            deadline = _time.monotonic() + 30.0
            while _time.monotonic() < deadline:
                try:
                    code, _ = _get(port, "/readyz")
                except OSError:
                    _time.sleep(0.05)
                    continue
                seen.setdefault("first_readyz", code)
                if code == 200:
                    seen["ready"] = True
                    seen["healthz"] = _get(port, "/healthz")[0]
                    seen["metrics"] = _get(port, "/metrics")[1]
                    return
                _time.sleep(0.05)

        t = threading.Thread(target=scrape, daemon=True)
        with FakeApiServer() as server:
            for i in range(4):
                server.add_node(f"n{i}", cpu="8", memory="16Gi",
                                pods=12)
            for j in range(24):
                server.add_pod(f"pod-{j:02d}", cpu="250m",
                               memory="256Mi", job=f"job{j // 6}")
            t.start()
            rc = run_loop(parse_args([
                "--k8s_apiserver_host=127.0.0.1",
                f"--k8s_apiserver_port={server.port}",
                "--watch=true",
                f"--metrics_port={port}",
                "--trace_profile=true",
                f"--trace_log={trace_path}",
                "--flow_scheduling_cost_model=quincy",
                "--polling_frequency=50000",
                "--max_rounds=8",
            ]))
            t.join(timeout=30.0)
        assert rc == 0
        assert seen.get("ready"), f"readyz never flipped: {seen}"
        assert seen["healthz"] == 200
        text = seen["metrics"]
        for family in (
            "poseidon_round_latency_ms_bucket",
            "poseidon_rounds_total",
            "poseidon_degrades_total",
            "poseidon_watch_resyncs_total",
            "poseidon_bind_failures_total",
            "poseidon_express_e2b_ms",
            "poseidon_ready 1",
        ):
            assert family in text, f"{family} missing from /metrics"
        from poseidon_tpu.trace import read_trace

        events = list(read_trace(str(trace_path)))
        kinds = {e.event for e in events}
        assert "SPAN" in kinds and "ROUND" in kinds
        lanes = {e.detail.get("lane") for e in events
                 if e.event == "ROUND" and e.detail
                 and e.detail.get("backend")}
        assert "watch+pipelined" in lanes
