"""Dense-memory envelope guard (round-4 verdict, Next #6).

The dense [Tp, Mp] cost table is the solve's dominant HBM footprint.
Nothing used to check it before ``_densify``/``_redensify`` — a 64k-task
x 16k-machine cluster would OOM mid-solve instead of degrading. Now
``check_table_budget`` gates every densify entry (front door, resident
round, what-if batch) and oversize instances fall back to the oracle
loudly, like the cost-domain guard.
"""

import numpy as np
import pytest

from poseidon_tpu.graph.builder import FlowGraphBuilder
from poseidon_tpu.ops import dense_auction
from poseidon_tpu.ops.dense_auction import (
    DenseMemoryTooLarge,
    check_table_budget,
)
from poseidon_tpu.ops.resident import ResidentSolver
from poseidon_tpu.oracle import solve_oracle
from poseidon_tpu.solver import solve_scheduling

from tests.helpers import price, random_cluster


class TestTableBudget:
    def test_flagship_fits(self):
        # the BASELINE flagship table is [10240, 1024] i32 = 40 MiB
        check_table_budget(10240, 1024)

    def test_64k_x_16k_exceeds(self):
        # a 64k-task x 16k-machine cluster is a 4 GiB table — over the
        # 2 GiB default budget; must raise, not OOM later
        with pytest.raises(DenseMemoryTooLarge):
            check_table_budget(65536, 16384)

    def test_what_if_batch_scales_with_variants(self):
        check_table_budget(4096, 1024, n_variants=64)   # 1 GiB: fits
        with pytest.raises(DenseMemoryTooLarge):
            check_table_budget(16384, 1024, n_variants=64)  # 4 GiB

    def test_synthetic_60k_x_16k_instance_falls_back(self):
        """A real 60k-task x 16k-machine instance, end to end through
        the builder and extraction: the guard fires BEFORE any device
        allocation (the ~4 GiB padded table never exists), and with
        the fallback disabled the front door surfaces the typed error
        instead of OOMing."""
        from poseidon_tpu.graph.builder import FlowGraphBuilder
        from poseidon_tpu.models import build_cost_inputs, get_cost_model
        from poseidon_tpu.ops.dense_auction import build_dense_instance
        from poseidon_tpu.ops.transport import extract_instance
        from poseidon_tpu.synth import make_synthetic_cluster

        cluster = make_synthetic_cluster(
            16_000, 60_000, seed=0, prefs_per_task=0
        )
        net, meta = FlowGraphBuilder().build(cluster)
        inputs = build_cost_inputs(net, meta)
        net = net.with_costs(get_cost_model("trivial")(inputs))
        inst = extract_instance(net, meta)
        with pytest.raises(DenseMemoryTooLarge):
            build_dense_instance(inst)
        with pytest.raises(DenseMemoryTooLarge):
            solve_scheduling(
                net, meta, oracle_fallback=False, small_to_oracle=False
            )


class TestFrontDoorDegrade:
    def test_solve_scheduling_degrades_to_oracle(self, monkeypatch):
        monkeypatch.setattr(
            dense_auction, "DENSE_TABLE_BUDGET_BYTES", 1024
        )
        cluster = random_cluster(np.random.default_rng(41), 6, 30)
        net, meta = FlowGraphBuilder().build(cluster)
        net = price(net, meta, "trivial", cluster)
        out = solve_scheduling(net, meta, small_to_oracle=False)
        assert out.backend == "oracle:memory-envelope"
        o = solve_oracle(net, algorithm="cost_scaling")
        assert out.exact and out.cost == o.cost

    def test_raises_when_fallback_disabled(self, monkeypatch):
        monkeypatch.setattr(
            dense_auction, "DENSE_TABLE_BUDGET_BYTES", 1024
        )
        cluster = random_cluster(np.random.default_rng(43), 6, 30)
        net, meta = FlowGraphBuilder().build(cluster)
        net = price(net, meta, "trivial", cluster)
        with pytest.raises(DenseMemoryTooLarge):
            solve_scheduling(
                net, meta, oracle_fallback=False, small_to_oracle=False
            )


class TestResidentDegrade:
    def _round(self, cluster, solver):
        arrays, meta = FlowGraphBuilder().build_arrays(cluster)
        pending = cluster.pending()
        return solver.run_round(
            arrays, meta, cost_model="trivial",
            cost_input_kwargs=dict(
                task_cpu_milli=np.array(
                    [int(t.cpu_request * 1000) for t in pending]
                ),
                task_mem_kb=np.array(
                    [t.memory_request_kb for t in pending]
                ),
            ),
        )

    def test_resident_round_degrades_loudly(self, monkeypatch):
        monkeypatch.setattr(
            dense_auction, "DENSE_TABLE_BUDGET_BYTES", 1024
        )
        cluster = random_cluster(np.random.default_rng(47), 6, 30)
        solver = ResidentSolver(small_to_oracle=False)
        out = self._round(cluster, solver)
        assert out.backend == "oracle:memory-envelope"
        assert out.converged
        assert (out.assignment >= 0).any()
        assert solver.warm is None  # stale warm state dropped

    def test_what_if_guard(self, monkeypatch):
        from poseidon_tpu.ops.batch import solve_what_if
        from poseidon_tpu.ops.transport import extract_instance

        cluster = random_cluster(np.random.default_rng(49), 6, 30)
        net, meta = FlowGraphBuilder().build(cluster)
        net = price(net, meta, "trivial", cluster)
        inst = extract_instance(net, meta)
        # budget admits one table but not 64 of them
        from poseidon_tpu.graph.network import pad_bucket

        tp = pad_bucket(inst.n_tasks)
        mp = pad_bucket(inst.n_machines)
        monkeypatch.setattr(
            dense_auction, "DENSE_TABLE_BUDGET_BYTES", tp * mp * 4 * 8
        )
        with pytest.raises(DenseMemoryTooLarge):
            solve_what_if(inst, n_variants=64, seed=1)
