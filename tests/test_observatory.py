"""The quality observatory: per-pod lifecycle tracing, the shadow
placement audit, and the declarative SLO engine (ISSUE 14).

Covers the acceptance surface directly:

- lifecycle differential: the SAME pod's event-to-confirmed latency
  via the tick lane and the express lane agrees with the
  driver-observed wall time (monotonic-clock contract), and a
  restart-replayed bind closes its PRE-CRASH timeline (wall-stamp
  seed from the journal) instead of minting a new one;
- shadow audit: regret is bit-zero on a certified-exact steady state,
  measurably positive on the config-6 drift cluster (including via
  EMPTY place-only rounds), and recovers to zero when rebalancing
  settles;
- SLO engine: grammar, multi-window burn rates, and the breach latch
  firing EXACTLY once per breach window;
- trace-ring overwrite visibility and the label-cardinality bounds
  fuzz (out-of-vocabulary labels fold, never mint).
"""

import dataclasses
import json
import random
import re
import time
import urllib.error
import urllib.request

import pytest

from poseidon_tpu.bridge import SchedulerBridge, SchedulerStats
from poseidon_tpu.cluster import Task
from poseidon_tpu.obs import (
    HealthState,
    LifecycleTracker,
    MetricsRegistry,
    ObsServer,
    SchedulerMetrics,
    ShadowAuditor,
    SloEngine,
)
from poseidon_tpu.obs.lifecycle import LANES, bounded_lane
from poseidon_tpu.obs.metrics import (
    _BUILD_MODES,
    _DEGRADE_WHYS,
    build_mode_label,
    degrade_why_label,
    lane_label,
    resource_label,
    resync_reason_label,
)
from poseidon_tpu.obs.slo import SloParseError, parse_objective
from poseidon_tpu.synth import config6_rebalance, make_synthetic_cluster
from poseidon_tpu.trace import TraceGenerator

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")

# module-level jitted probe (PTA003: no inline jax.jit): the compile
# telemetry test drives one backend compile through it
import jax  # noqa: E402

_COMPILE_PROBE = jax.jit(lambda x: x * 3 + 1)


def _metrics() -> SchedulerMetrics:
    return SchedulerMetrics(MetricsRegistry())


def _observed_bridge(**kw):
    m = kw.pop("metrics", None) or _metrics()
    lc = LifecycleTracker(m)
    br = SchedulerBridge(
        cost_model="quincy", small_to_oracle=False, metrics=m,
        lifecycle=lc, **kw,
    )
    return br, lc, m


def _settle(br, rounds=1):
    last = None
    for _ in range(rounds):
        last = br.run_scheduler()
        for uid, mach in last.bindings.items():
            br.confirm_binding(uid, mach)
        for uid, (_f, to) in last.migrations.items():
            br.confirm_migration(uid, to)
        for uid in last.preemptions:
            br.confirm_preemption(uid)
    return last


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------


class TestLifecycle:
    def test_tick_lane_e2c_matches_driver_wall(self):
        br, lc, m = _observed_bridge()
        c = make_synthetic_cluster(12, 20, seed=0, prefs_per_task=2)
        br.observe_nodes(list(c.machines))
        t0 = time.perf_counter()
        br.observe_pods(list(c.tasks))
        _settle(br)
        wall_ms = (time.perf_counter() - t0) * 1000
        assert lc.closed_total > 0
        uid, lane, e2c = lc.last_closed
        assert lane == "tick"
        # the e2c clock starts at first sight (inside the observe
        # above) and stops at confirm — it must sit inside the
        # driver's own wall measurement of the same span
        assert 0 < e2c <= wall_ms + 1.0
        text = m.registry.render()
        assert 'poseidon_pod_e2c_ms_bucket{lane="tick"' in text

    def test_same_pod_tick_then_express_lanes_agree(self):
        """The SAME uid rides the tick lane, retires, then rides the
        express lane: each close lands in its own lane's histogram
        and each e2c agrees with the driver-observed wall time."""
        br, lc, m = _observed_bridge(express_lane=True)
        c = make_synthetic_cluster(16, 30, seed=1, prefs_per_task=2)
        br.observe_nodes(list(c.machines))
        br.observe_pods(list(c.tasks))
        _settle(br)
        target = list(br.machines)[0]
        pod = Task(uid="same-pod", cpu_request=0.1,
                   memory_request_kb=128, data_prefs={target: 300})
        # tick lane first
        t0 = time.perf_counter()
        br.observe_pod_event("ADDED", pod)
        res = _settle(br)
        tick_wall = (time.perf_counter() - t0) * 1000
        assert "same-pod" in res.bindings
        uid, lane, tick_e2c = lc.last_closed
        assert (uid, lane) == ("same-pod", "tick")
        assert 0 < tick_e2c <= tick_wall + 1.0
        # retire it, then the SAME uid arrives again via express
        br.observe_pod_event(
            "DELETED", br.tasks["same-pod"]
        )
        _settle(br)  # refresh the express context
        assert br.solver.express_ready
        t1 = time.perf_counter()
        out = br.express_batch(
            [("ADDED", pod)], t_event=t1, t_events=[t1]
        )
        assert out is not None and "same-pod" in out.bindings
        br.confirm_binding("same-pod", out.bindings["same-pod"])
        express_wall = (time.perf_counter() - t1) * 1000
        uid, lane, express_e2c = lc.last_closed
        assert (uid, lane) == ("same-pod", "express")
        assert 0 < express_e2c <= express_wall + 1.0
        text = m.registry.render()
        assert 'poseidon_pod_e2c_ms_bucket{lane="tick"' in text
        assert 'poseidon_pod_e2c_ms_bucket{lane="express"' in text

    def test_restart_replay_closes_pre_crash_timeline(self, tmp_path):
        """A bind journaled with its lifecycle wall stamp before a
        crash closes into lane="restart" spanning the PRE-crash wait —
        and does not mint a fresh open timeline."""
        from poseidon_tpu.apiclient.client import K8sApiClient
        from poseidon_tpu.apiclient.fake_server import FakeApiServer
        from poseidon_tpu.ha import ActuationJournal, replay_journal

        path = str(tmp_path / "j.jsonl")
        j = ActuationJournal(path)
        pre_crash_us = int((time.time() - 5.0) * 1e6)
        j.intents([{
            "op": "bind", "uid": "default/p000", "machine": "n0",
            "t_event_us": pre_crash_us,
        }], 7)
        j.close()
        j2 = ActuationJournal(path)  # the restart
        entries = j2.incomplete()
        assert entries[0].t_event_us == pre_crash_us
        m = _metrics()
        lc = LifecycleTracker(m)  # fresh process: no open timelines
        with FakeApiServer() as server:
            server.add_node("n0", cpu="8", memory="16Gi", pods=8)
            server.add_pod("p000", cpu="250m", memory="256Mi")
            client = K8sApiClient("127.0.0.1", server.port)
            out = replay_journal(
                client, entries, journal=j2, lifecycle=lc,
            )
        j2.close()
        assert out["replayed"] == 1
        uid, lane, e2c = lc.last_closed
        assert (uid, lane) == ("default/p000", "restart")
        # spans the pre-crash wait (~5s), not the replay's own few ms
        assert 4500 < e2c < 60_000
        assert "default/p000" not in lc.open  # no new timeline minted
        assert 'lane="restart"' in m.registry.render()

    def test_unconfirmed_pod_keeps_timeline_open(self):
        br, lc, m = _observed_bridge()
        c = make_synthetic_cluster(8, 10, seed=2)
        br.observe_nodes(list(c.machines))
        br.observe_pods(list(c.tasks))
        res = br.run_scheduler()  # decided but never confirmed
        assert res.bindings
        for uid in res.bindings:
            assert uid in lc.open
        assert lc.closed_total == 0

    def test_retired_pod_drops_timeline(self):
        br, lc, _ = _observed_bridge()
        c = make_synthetic_cluster(8, 10, seed=2)
        br.observe_nodes(list(c.machines))
        br.observe_pods(list(c.tasks))
        uid = next(iter(br.tasks))
        assert uid in lc.open
        br.observe_pod_event("DELETED", br.tasks[uid])
        assert uid not in lc.open

    def test_open_timeline_bound_drops_and_counts(self):
        m = _metrics()
        lc = LifecycleTracker(m, max_open=4)
        for i in range(10):
            lc.stamp_event(f"p{i}")
        assert len(lc.open) == 4
        assert lc.dropped == 6
        assert "poseidon_lifecycle_dropped_total 6" in \
            m.registry.render()

    def test_backdate_event_only_moves_earlier(self):
        lc = LifecycleTracker()
        lc.stamp_event("p")
        t0 = lc.open["p"].t_event
        w0 = lc.open["p"].t_event_wall_us
        lc.backdate_event("p", t0 - 1.0)
        assert lc.open["p"].t_event == t0 - 1.0
        # the wall twin (the journal's restart seed) backdates by the
        # same delta, so a restart e2c also spans from the receipt
        assert abs((w0 - lc.open["p"].t_event_wall_us) - 1e6) < 2e3
        lc.backdate_event("p", t0 + 5.0)  # later: ignored
        assert lc.open["p"].t_event == t0 - 1.0

    def test_failed_post_reopens_timeline_from_original_stamp(self):
        """The pipelined driver confirms optimistically; a failed POST
        (binding_failed -> revoke) must REOPEN the timeline from its
        original event stamp so the pod's real end-to-end wait is
        still measured at the eventual successful bind."""
        br, lc, m = _observed_bridge()
        c = make_synthetic_cluster(8, 10, seed=2)
        br.observe_nodes(list(c.machines))
        br.observe_pods(list(c.tasks))
        res = br.run_scheduler()
        uid, mach = next(iter(res.bindings.items()))
        t_orig = lc.open[uid].t_event
        br.confirm_binding(uid, mach)   # optimistic (pipelined)
        assert uid not in lc.open
        first = lc.last_closed[2]
        br.binding_failed(uid)          # the POST failed
        assert uid in lc.open
        assert lc.open[uid].t_event == t_orig
        # the eventual successful bind spans the FULL wait
        time.sleep(0.01)
        res2 = br.run_scheduler()
        assert uid in res2.bindings
        br.confirm_binding(uid, res2.bindings[uid])
        assert lc.last_closed[0] == uid
        assert lc.last_closed[2] > first + 9.0

    def test_stage_stamps_observable_at_close(self):
        lc = LifecycleTracker()
        lc.stamp_event("p")
        lc.stamp_decided("p", "tick")
        lc.stamp("p", "journal")
        lc.stamp("p", "posted")
        lc.close_confirmed("p")
        assert set(lc.last_closed_stages) == {
            "decided", "journal", "posted"
        }

    def test_unsched_wait_age_gauges(self):
        br, lc, m = _observed_bridge()
        c = make_synthetic_cluster(8, 40, seed=0)  # oversubscribed
        br.observe_nodes(list(c.machines))
        br.observe_pods(list(c.tasks))
        res = _settle(br)
        assert res.unscheduled
        text = m.registry.render()
        assert 'poseidon_unsched_wait_rounds{q="p50"}' in text
        assert 'poseidon_unsched_wait_rounds{q="max"}' in text
        # every pod the round left behind has aged at least once
        # (synth seeds some pods with prior wait_rounds, so max >= 1)
        mt = re.search(
            r'poseidon_unsched_wait_rounds\{q="max"\} (\d+)', text
        )
        assert mt and int(mt.group(1)) >= 1


# ---------------------------------------------------------------------------
# shadow audit
# ---------------------------------------------------------------------------


class TestShadowAudit:
    def test_regret_bit_zero_on_certified_steady_state(self):
        aud = ShadowAuditor(sample_every=1, background=False)
        br = SchedulerBridge(
            cost_model="quincy", small_to_oracle=False, auditor=aud,
        )
        c = make_synthetic_cluster(16, 30, seed=1, prefs_per_task=2)
        br.observe_nodes(list(c.machines))
        br.observe_pods(list(c.tasks))
        _settle(br)        # certified round, placements confirmed
        br.run_scheduler()  # the next round's begin captures them
        out = aud.run_pending()
        assert out is not None and not out.error
        assert out.regret == 0
        assert out.status_quo_cost == out.optimal_cost
        assert out.drift_pods == 0

    def test_drift_cluster_regret_positive_even_on_empty_rounds(self):
        """The config-6 drift cluster under a PLACE-ONLY bridge rounds
        empty forever (everything is RUNNING) — the audit must still
        fire and expose the drift as positive regret."""
        aud = ShadowAuditor(sample_every=1, background=False)
        br = SchedulerBridge(cost_model="quincy", auditor=aud)
        dc = config6_rebalance(48, 120, seed=0)
        br.observe_nodes(dc.machines)
        br.observe_pods(dc.tasks)
        r = br.run_scheduler()
        assert r.stats.backend == ""  # empty round, nothing pending
        out = aud.run_pending()
        assert out is not None and not out.error
        assert out.regret > 0
        assert out.drift_pods > 0

    def test_rebalancing_drives_regret_to_zero(self):
        aud = ShadowAuditor(sample_every=1, background=False)
        br = SchedulerBridge(
            cost_model="quincy", enable_preemption=True,
            migration_hysteresis=20, max_migrations_per_round=64,
            auditor=aud,
        )
        dc = config6_rebalance(48, 120, seed=0)
        br.observe_nodes(dc.machines)
        br.observe_pods(dc.tasks)
        regrets = []
        for _ in range(8):
            _settle(br)
            out = aud.run_pending()
            if out is not None:
                regrets.append(out.regret)
        assert regrets[0] > 0          # drifted at first sight
        assert regrets[-1] == 0        # settled: promise measured
        assert sorted(regrets, reverse=True) == regrets  # monotone

    def test_fragmentation_index_bounded_sku_classes(self):
        from poseidon_tpu.obs.audit import (
            AuditSnapshot,
            fragmentation_index,
        )
        from poseidon_tpu.cluster import Machine, TaskPhase

        machines = [
            Machine(
                name=f"m{i}", rack="r0", cpu_capacity=float(4 + i),
                cpu_allocatable=4.0, memory_capacity_kb=1 << 20,
                memory_allocatable_kb=1 << 20, max_tasks=4,
            )
            for i in range(12)  # 12 distinct SKUs > MAX_SKU_CLASSES
        ]
        tasks = [
            Task(uid="t0", cpu_request=0.1, memory_request_kb=1,
                 phase=TaskPhase.RUNNING, machine="m0"),
        ]
        snap = AuditSnapshot(
            round_num=1, cost_model="quincy", hysteresis=0,
            machines=machines, tasks=tasks, uids=["t0"],
            names=[m.name for m in machines],
            task_usage=None, machine_load=None,
            machine_mem_free=None,
        )
        frag = fragmentation_index(snap)
        # content-keyed labels (stable under fleet churn), capped at
        # MAX_SKU_CLASSES + "other"
        assert len(frag) <= 9 and "other" in frag
        assert frag["4c-1g-4s"] == 3  # m0 has one of four seats used
        # stability: a new SKU joining must not remap existing labels
        snap.machines = machines + [dataclasses.replace(
            machines[0], name="new", cpu_capacity=1.0,
        )]
        frag2 = fragmentation_index(snap)
        assert frag2["4c-1g-4s"] == 3

    def test_vanished_sku_class_is_zeroed(self):
        from poseidon_tpu.obs.audit import AuditResult

        m = _metrics()
        m.record_audit(AuditResult(
            round_num=1, frag_slots={"8c-16g-12s": 5, "4c-8g-8s": 2},
        ))
        m.record_audit(AuditResult(
            round_num=2, frag_slots={"8c-16g-12s": 4},
        ))
        text = m.registry.render()
        assert 'poseidon_audit_frag_slots{sku="8c-16g-12s"} 4' in text
        # the drained class reads 0, not its last live value
        assert 'poseidon_audit_frag_slots{sku="4c-8g-8s"} 0' in text

    def test_background_worker_and_metrics(self):
        m = _metrics()
        aud = ShadowAuditor(
            metrics=m, sample_every=1, background=True,
        )
        try:
            br = SchedulerBridge(
                cost_model="quincy", small_to_oracle=False,
                metrics=m, auditor=aud,
            )
            c = make_synthetic_cluster(12, 20, seed=0)
            br.observe_nodes(list(c.machines))
            br.observe_pods(list(c.tasks))
            _settle(br, rounds=2)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                with aud._lock:
                    if aud.completed:
                        break
                time.sleep(0.05)
            assert aud.completed >= 1
            text = m.registry.render()
            assert "poseidon_audit_regret" in text
            assert 'poseidon_audit_runs_total{outcome="ok"}' in text
        finally:
            aud.stop()

    def test_capture_skips_when_worker_busy(self):
        aud = ShadowAuditor(sample_every=1, background=False)
        br = SchedulerBridge(cost_model="quincy", auditor=aud)
        dc = config6_rebalance(24, 60, seed=0)
        br.observe_nodes(dc.machines)
        br.observe_pods(dc.tasks)
        for _ in range(4):  # queue bound is 2; the rest are skipped
            br.run_scheduler()
        assert aud.skipped == 2
        assert aud.run_pending() is not None

    def test_audit_error_is_counted_not_raised(self):
        m = _metrics()
        aud = ShadowAuditor(
            metrics=m, sample_every=1, background=False,
        )
        br = SchedulerBridge(cost_model="quincy", auditor=aud)
        dc = config6_rebalance(24, 60, seed=0)
        br.observe_nodes(dc.machines)
        br.observe_pods(dc.tasks)
        br.run_scheduler()
        # doctor the queued snapshot into an unpriceable one
        snap = aud._q.get_nowait()
        snap.cost_model = "no-such-model"
        aud._q.put_nowait(snap)
        out = aud.run_pending()
        assert out.error
        assert aud.failures == 1
        assert 'poseidon_audit_runs_total{outcome="error"}' in \
            m.registry.render()

    def test_no_capture_without_running_tasks(self):
        aud = ShadowAuditor(sample_every=1, background=False)
        br = SchedulerBridge(cost_model="quincy", auditor=aud)
        c = make_synthetic_cluster(8, 10, seed=0)
        br.observe_nodes(list(c.machines))
        br.observe_pods(list(c.tasks))  # all pending, none running
        br.run_scheduler()
        assert aud.run_pending() is None


# ---------------------------------------------------------------------------
# the SLO engine
# ---------------------------------------------------------------------------


class TestSloGrammar:
    def test_histogram_objective(self):
        o = parse_objective("e2b_p99_ms < 10 by lane=express")
        assert o.kind == "histogram"
        assert o.family == "poseidon_express_e2b_ms"
        assert o.op == "<" and o.threshold == 10.0
        assert abs(o.budget - 0.01) < 1e-9
        assert o.labels == (("lane", "express"),)

    def test_percentile_is_the_budget(self):
        assert abs(
            parse_objective("e2c_p50_ms < 100").budget - 0.5
        ) < 1e-9
        assert abs(
            parse_objective("round_p999_ms < 500").budget - 0.001
        ) < 1e-9
        # ambiguous spellings rejected: p100 would silently read as
        # p10 (budget 0.9) and never fire
        for bad in ("e2b_p100_ms < 10", "e2b_p950_ms < 10",
                    "e2b_p0_ms < 10"):
            with pytest.raises(SloParseError, match="percentile"):
                parse_objective(bad)

    def test_gauge_and_bool_objectives(self):
        o = parse_objective("regret == 0")
        assert o.kind == "gauge"
        assert o.family == "poseidon_audit_regret"
        r = parse_objective("ready")
        assert (r.op, r.threshold) == ("==", 1.0)

    def test_threshold_below_smallest_bucket_rejected(self):
        # E2B buckets start at 0.25: a '< 0.2' objective has no edge
        # to snap down to and would read 'all good' as 'all bad'
        with pytest.raises(SloParseError, match="smallest bucket"):
            SloEngine(["e2b_p99_ms < 0.2"], metrics=_metrics())
        # unregistered families stay permissive (nothing to check)
        SloEngine(["e2b_p99_ms < 0.2"], metrics=None)

    def test_parse_errors(self):
        for bad in (
            "nope_p99_ms < 10",      # unknown source
            "regret",                # non-bool gauge without op
            "e2b_p99_ms",            # histogram without op
            "e2b_p99_ms > 10",       # percentiles are upper bounds
            "e2b_p99_ms < 10 by lane",  # bad by clause
        ):
            with pytest.raises(SloParseError):
                parse_objective(bad)


class TestSloEngine:
    def test_gauge_breach_fires_exactly_once_per_window(self):
        m = _metrics()
        trace = TraceGenerator()
        eng = SloEngine(
            ["regret == 0"], metrics=m, trace=trace,
            short_window=2, long_window=4,
        )
        breaches = lambda: sum(  # noqa: E731
            1 for e in trace.events if e.event == "SLO_BREACH"
        )
        m.audit_regret.set(0)
        for i in range(4):
            eng.evaluate(i)
        assert breaches() == 0
        m.audit_regret.set(137)  # the breach window opens
        for i in range(10):      # burns for many rounds...
            eng.evaluate(10 + i)
        assert breaches() == 1   # ...but fires exactly once
        st = eng.status()["objectives"][0]
        assert st["healthy"] is False
        assert st["breaches"] == 1
        # recovery clears the latch...
        m.audit_regret.set(0)
        for i in range(6):
            eng.evaluate(30 + i)
        assert eng.status()["objectives"][0]["healthy"] is True
        # ...and the NEXT breach window fires exactly once again
        m.audit_regret.set(9)
        for i in range(10):
            eng.evaluate(50 + i)
        assert breaches() == 2
        text = m.registry.render()
        assert "poseidon_slo_breaches_total" in text
        assert 'poseidon_slo_burn_rate{slo="regret == 0",window="short"}' \
            in text

    def test_histogram_objective_burn(self):
        m = _metrics()
        eng = SloEngine(
            ["e2b_p99_ms < 10"], metrics=m,
            short_window=2, long_window=4,
        )
        # healthy traffic: everything under threshold
        for _ in range(3):
            m.record_express_batch([1.0, 2.0, 3.0])
            eng.evaluate(0)
        st = eng.status()["objectives"][0]
        assert st["healthy"] is True and st["burn_short"] == 0.0
        # now 100% of samples over threshold: burn = 1/budget = 100x
        for _ in range(4):
            m.record_express_batch([50.0, 80.0])
            eng.evaluate(1)
        st = eng.status()["objectives"][0]
        assert st["burn_short"] > 1.0
        assert st["healthy"] is False

    def test_ready_objective_tracks_latch(self):
        m = _metrics()
        health = HealthState(ready_gauge=m.ready)
        eng = SloEngine(
            ["ready"], metrics=m, short_window=2, long_window=2,
        )
        eng.evaluate(1)
        eng.evaluate(2)
        assert eng.status()["objectives"][0]["healthy"] is False
        health.mark_seeded()
        health.mark_round("dense_auction")
        for i in range(3):
            eng.evaluate(3 + i)
        assert eng.status()["objectives"][0]["healthy"] is True

    def test_inf_percentile_never_breaks_render_or_json(self):
        """A percentile beyond the histogram's top bucket is inf:
        the metrics render must spell it +Inf (not crash with
        OverflowError), and /slo JSON must stay strict (null)."""
        m = _metrics()
        eng = SloEngine(
            ["e2b_p99_ms < 10"], metrics=m,
            short_window=1, long_window=1,
        )
        # every sample beyond the 250ms top E2B bucket
        m.record_express_batch([10_000.0, 20_000.0])
        eng.evaluate(1)
        text = m.registry.render()  # must not raise
        assert 'poseidon_slo_value{slo="e2b_p99_ms < 10"} +Inf' \
            in text
        doc = json.loads(json.dumps(eng.status()))  # strict round-trip
        assert doc["objectives"][0]["value"] is None

    def test_no_samples_is_healthy(self):
        eng = SloEngine(
            ["e2b_p99_ms < 10 by lane=express"], metrics=_metrics(),
        )
        eng.evaluate(1)
        st = eng.status()["objectives"][0]
        assert st["healthy"] is True and st["burn_short"] == 0.0

    def test_slo_endpoint(self):
        m = _metrics()
        eng = SloEngine(["regret == 0"], metrics=m)
        eng.evaluate(1)
        srv = ObsServer(m.registry, HealthState(), port=0, slo=eng)
        with srv:
            url = f"http://127.0.0.1:{srv.port}/slo"
            with urllib.request.urlopen(url, timeout=5) as resp:
                doc = json.loads(resp.read())
            assert doc["evaluations"] == 1
            assert doc["objectives"][0]["spec"] == "regret == 0"
        srv2 = ObsServer(m.registry, HealthState(), port=0)
        with srv2:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv2.port}/slo", timeout=5
                )
            assert ei.value.code == 404
            # slo assigned AFTER start() must take effect (handlers
            # read the attribute per request, not a start-time
            # snapshot)
            srv2.slo = eng
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv2.port}/slo", timeout=5
            ) as resp:
                assert json.loads(resp.read())["evaluations"] == 1

    def test_breach_lands_in_trace_report(self, tmp_path):
        path = tmp_path / "t.jsonl"
        with open(path, "w") as fh:
            trace = TraceGenerator(sink=fh)
            m = _metrics()
            eng = SloEngine(
                ["regret == 0"], metrics=m, trace=trace,
                short_window=1, long_window=1,
            )
            m.audit_regret.set(5)
            eng.evaluate(3)
        from poseidon_tpu.obs.report import (
            analyze_trace,
            render_report,
        )

        data = analyze_trace(str(path))
        assert data["slo_breaches"] == {"regret == 0": 1}
        assert "SLO breaches" in render_report(data)


# ---------------------------------------------------------------------------
# trace-ring overwrite visibility
# ---------------------------------------------------------------------------


class TestTraceRingDrop:
    def test_tiny_ring_counts_overwrites(self):
        tg = TraceGenerator(buffer_events=4)
        for i in range(10):
            tg.emit("SUBMIT", task=f"p{i}")
        assert len(tg.events) == 4
        assert tg.dropped_total == 6

    def test_sinked_trace_never_drops(self, tmp_path):
        with open(tmp_path / "t.jsonl", "w") as fh:
            tg = TraceGenerator(sink=fh, buffer_events=2)
            for i in range(10):
                tg.emit("SUBMIT", task=f"p{i}")
        assert tg.dropped_total == 0

    def test_bridge_mirrors_drops_into_metric(self):
        m = _metrics()
        tiny = TraceGenerator(buffer_events=8)
        br = SchedulerBridge(
            cost_model="quincy", small_to_oracle=False,
            trace=tiny, metrics=m,
        )
        c = make_synthetic_cluster(8, 30, seed=0)
        br.observe_nodes(list(c.machines))
        br.observe_pods(list(c.tasks))  # 30 SUBMITs wrap the ring
        _settle(br)
        assert tiny.dropped_total > 0
        mt = re.search(
            r"poseidon_trace_dropped_total (\d+)",
            m.registry.render(),
        )
        assert mt and int(mt.group(1)) == tiny.dropped_total


# ---------------------------------------------------------------------------
# label-cardinality bounds (fuzz)
# ---------------------------------------------------------------------------


def _garbage(rng, n=24):
    alphabet = (
        "abcdefghijklmnopqrstuvwxyz0123456789-_./:; "
        "ABCDEFGHIJKLMNOPQRSTUVWXYZ"
    )
    return "".join(
        rng.choice(alphabet) for _ in range(rng.randint(1, n))
    )


class TestLabelCardinalityBounds:
    """Out-of-vocabulary label inputs must FOLD to a bounded bucket,
    never mint a new series — unbounded label churn is how a metrics
    endpoint ODs its scraper."""

    def test_fold_functions_are_total_and_bounded(self):
        rng = random.Random(0)
        seen = set()
        for _ in range(500):
            g = _garbage(rng)
            seen.add(lane_label(g))
            seen.add(degrade_why_label(g))
            seen.add(build_mode_label(g))
            seen.add(resource_label(g))
            seen.add(resync_reason_label(g))
            seen.add(bounded_lane(g))
        from poseidon_tpu.obs.metrics import _LANE_PARTS

        vocab = (
            _LANE_PARTS | _DEGRADE_WHYS | _BUILD_MODES
            | set(LANES)
            | {"other", "round", "nodes", "pods",
               "gone", "stale", "decode", "error", "none",
               "delta", "full", "legacy"}
        )
        assert seen <= vocab

    def test_recording_garbage_never_mints_labels(self):
        rng = random.Random(1)
        m = _metrics()
        for _ in range(100):
            m.record_degrade(_garbage(rng))
            m.record_express_degrade(_garbage(rng))
            m.record_resync(_garbage(rng))
            m.record_reconnect(_garbage(rng))
            m.record_pod_e2c(1.0, _garbage(rng))
            stats = SchedulerStats(
                round_num=1, lane=_garbage(rng),
                build_mode=_garbage(rng),
                backend="oracle:" + _garbage(rng),
                total_ms=1.0,
            )
            m.record_round(stats)
        text = m.registry.render()
        values = set(re.findall(r'(\w+)="([^"]*)"', text))
        for key, val in values:
            if key in ("lane", "why", "reason", "resource",
                       "build_mode"):
                assert val in (
                    _DEGRADE_WHYS | _BUILD_MODES | set(LANES)
                    | {"other", "round", "express",
                       "gone", "stale", "decode", "error",
                       "unconfirmed", "domain", "uncertified",
                       "change-cap", "batch-size", "rows-exhausted",
                       "no-context", "round-in-flight",
                       "aggregation", "prefs", "vocabulary",
                       "nodes", "pods"}
                ), (key, val)
        # and the degrade counter's series count stays bounded no
        # matter how much garbage went in
        assert text.count("poseidon_degrades_total{") <= \
            len(_DEGRADE_WHYS) + 1


# ---------------------------------------------------------------------------
# device telemetry satellites
# ---------------------------------------------------------------------------


class TestDeviceTelemetry:
    def test_predicted_bytes_gauge_set_by_dense_round(self):
        m = _metrics()
        br = SchedulerBridge(
            cost_model="quincy", small_to_oracle=False, metrics=m,
        )
        c = make_synthetic_cluster(12, 20, seed=0)
        br.observe_nodes(list(c.machines))
        br.observe_pods(list(c.tasks))
        res = br.run_scheduler()
        assert res.stats.backend == "dense_auction"
        mt = re.search(
            r'poseidon_device_hbm_bytes\{kind="predicted"\} (\d+)',
            m.registry.render(),
        )
        assert mt and int(mt.group(1)) > 0

    def test_live_hbm_is_gated_on_platform_support(self):
        m = _metrics()
        out = m.record_live_hbm()
        text = m.registry.render()
        if out is None:
            # CPU backends expose no memory_stats: nothing published
            assert 'kind="live"' not in text
        else:
            assert 'kind="live"' in text

    def test_compile_latency_histogram_via_monitoring_seam(self):
        import jax.numpy as jnp

        from poseidon_tpu.guards import set_compile_duration_sink

        m = _metrics()
        if not set_compile_duration_sink(m.record_compile):
            pytest.skip("jax.monitoring not available")
        try:
            # a fresh jitted shape forces one backend compile
            _COMPILE_PROBE(jnp.arange(173)).block_until_ready()
            mt = re.search(
                r"poseidon_xla_compile_ms_count (\d+)",
                m.registry.render(),
            )
            assert mt and int(mt.group(1)) >= 1
        finally:
            set_compile_duration_sink(None)
