"""Namespace-qualified pod identity (round-4 verdict, Next #3).

Pod names are only unique per namespace. The reference sidesteps this by
hardcoding namespace "default" into its bindings POST
(k8s_api_client.cc:222); this framework parses real namespaces, so its
task identity must be the qualified "ns/name" pair — two same-named pods
in different namespaces are distinct tasks with independent state and
independent bindings.
"""

from poseidon_tpu.apiclient import FakeApiServer, K8sApiClient
from poseidon_tpu.bridge import SchedulerBridge
from poseidon_tpu.cluster import Task


class TestTaskName:
    def test_qualified_uid_splits(self):
        t = Task(uid="team-a/worker-0", namespace="team-a")
        assert t.name == "worker-0"

    def test_bare_uid_passthrough(self):
        t = Task(uid="task-7")
        assert t.name == "task-7"


class TestSameNamedPodsAcrossNamespaces:
    def test_distinct_tasks_and_independent_bindings(self):
        with FakeApiServer() as server:
            server.add_node("n0", cpu="8", memory="16Gi", pods=10)
            server.add_node("n1", cpu="8", memory="16Gi", pods=10)
            # identical pod NAME in two namespaces, different shapes —
            # if identity collapsed to the bare name, one would
            # overwrite the other in the bridge maps
            server.add_pod(
                "app-0", namespace="alpha", cpu="250m", memory="256Mi",
                job="train",
            )
            server.add_pod(
                "app-0", namespace="beta", cpu="500m", memory="512Mi",
                job="train",
            )

            client = K8sApiClient("127.0.0.1", server.port)
            pods = client.all_pods()
            assert len(pods) == 2
            uids = {p.uid for p in pods}
            assert uids == {"alpha/app-0", "beta/app-0"}
            # same-named JOBS stay distinct too — an unqualified job
            # label would merge both namespaces' tasks under one
            # unscheduled aggregator in the flow graph
            assert {p.job_id for p in pods} == {
                "alpha/train", "beta/train",
            }
            by_uid = {p.uid: p for p in pods}
            assert by_uid["alpha/app-0"].cpu_request == 0.25
            assert by_uid["beta/app-0"].cpu_request == 0.5

            bridge = SchedulerBridge(cost_model="trivial")
            bridge.observe_nodes(client.all_nodes())
            bridge.observe_pods(pods)
            result = bridge.run_scheduler()
            # BOTH tasks schedule — no state collision ate one of them
            assert set(result.bindings) == {"alpha/app-0", "beta/app-0"}

            for uid, machine in result.bindings.items():
                task = bridge.tasks[uid]
                assert client.bind_pod_to_node(
                    task.name, machine, namespace=task.namespace
                )
            assert sorted(k for k, _ in server.bindings) == [
                "alpha/app-0", "beta/app-0",
            ]

            # next poll observes each binding on its own pod
            pods2 = {p.uid: p for p in client.all_pods()}
            for uid, machine in result.bindings.items():
                assert pods2[uid].machine == machine

    def test_qualified_uid_accepted_by_bindings_post(self):
        with FakeApiServer() as server:
            server.add_node("n0")
            server.add_pod("solo", namespace="gamma")
            client = K8sApiClient("127.0.0.1", server.port)
            # the qualifier inside the pod id wins over the namespace
            # keyword, so callers can pass the uid straight through
            assert client.bind_pod_to_node("gamma/solo", "n0")
            assert server.bindings == [("gamma/solo", "n0")]
