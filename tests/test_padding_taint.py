"""Tests for the padding-taint dataflow audit (analysis/padding_taint).

Unit coverage drives ``analyze_kernel`` over tiny hand-rolled traces
(fold-dominance, the bool-counting exemption, taint through scan
carries); the acceptance tests re-introduce the REAL bug the pass
exists to catch — reverting ``_express_step``'s arrival-lane mask
(PR 10's express cost regression, re-fixed this wave) must produce
unmasked tainted reduce_min candidates, and the shipped kernel must
not.

ISSUE naming note: the express lane's reductions live in
``_express_step`` (the shared step body ``_express_chain`` jits and
``_stream_chain`` scans); ``_express_patch`` is the price-patch
scatter and contains no reductions — "the express kernel path" means
the step body.
"""

from __future__ import annotations

import importlib.machinery
import importlib.util
import pathlib
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poseidon_tpu.analysis.padding_taint import analyze_kernel
from poseidon_tpu.compat import enable_x64
from poseidon_tpu.ops import resident as real_resident
from poseidon_tpu.ops.dense_auction import DenseInstance

REPO = pathlib.Path(__file__).resolve().parent.parent

# the two masked folds PR 10 added (and this wave's audit now proves
# load-bearing): reverting them re-creates the shipped bug
_MASKED_U = "jnp.min(jnp.where(arr_valid, u_u, 0)),"
_MASKED_W = "jnp.min(jnp.where(arr_valid, w_u, 0)),"


def _candidates(fn, *args):
    with enable_x64(True):
        closed = jax.make_jaxpr(fn)(*args)
    return [
        (c.primitive, c.function)
        for c in analyze_kernel("unit", closed)
    ]


class TestFoldDominance:
    def test_unmasked_fold_over_input_fires(self):
        x = np.zeros(8, np.int32)
        cands = _candidates(lambda x: jnp.min(x), x)
        assert any(p == "reduce_min" for p, _ in cands), cands

    def test_mask_at_the_fold_is_clean(self):
        x = np.zeros(8, np.int32)
        assert _candidates(
            lambda x: jnp.min(jnp.where(x >= 0, x, 0)), x
        ) == []

    def test_upstream_mask_does_not_dominate(self):
        """The laundering shape that shipped the real bug: a where on
        the way in, arithmetic after it, an unmasked fold at the end.
        The mask no longer dominates once the add re-mixes lanes."""
        x = np.zeros(8, np.int32)

        def laundered(x):
            y = jnp.where(x >= 0, x, 0)  # masked ... for now
            return jnp.min(y + 1)        # add kills domination

        assert _candidates(laundered, x) != []

    def test_scalar_inputs_are_clean(self):
        assert _candidates(
            lambda n: jnp.minimum(n, 0) * 2, np.int32(3)
        ) == []

    def test_bool_counting_fold_exempt(self):
        """jnp.sum over a mask is how padding predicates are BUILT —
        counting a tainted bool is not a finding, even through the
        dtype conversion sum inserts."""
        x = np.zeros(8, np.int32)
        assert _candidates(
            lambda x: jnp.sum(x >= 0, dtype=jnp.int32), x
        ) == []

    def test_reduce_and_over_tainted_mask_fires(self):
        """...but an unmasked jnp.all IS a finding: a padded row
        poisons a convergence certificate through exactly this."""
        x = np.zeros(8, np.int32)
        cands = _candidates(lambda x: jnp.all(x >= 0), x)
        assert any(p == "reduce_and" for p, _ in cands), cands

    def test_taint_flows_through_scan_carry(self):
        x = np.zeros((4, 8), np.int32)

        def scanned(x):
            def step(carry, row):
                return carry + row, jnp.min(carry)

            init = jnp.zeros(8, jnp.int32)
            _, outs = jax.lax.scan(step, init, x)
            return outs

        cands = _candidates(scanned, x)
        assert any(p == "reduce_min" for p, _ in cands), cands


# ---------------------------------------------------------------------------
# acceptance: the reverted real bug
# ---------------------------------------------------------------------------


def _load_reverted_resident(tmp_path):
    """Load ops/resident.py with the arrival-lane masks stripped, as a
    uniquely-named module (its own DenseTopology pytree registration
    does not collide with the real one)."""
    src = (REPO / "poseidon_tpu/ops/resident.py").read_text()
    assert _MASKED_U in src and _MASKED_W in src, (
        "acceptance anchor moved: update _MASKED_U/_MASKED_W"
    )
    bad = src.replace(_MASKED_U, "jnp.min(u_u),").replace(
        _MASKED_W, "jnp.min(w_u),"
    )
    p = tmp_path / "resident_reverted.py"
    p.write_text(bad)
    loader = importlib.machinery.SourceFileLoader(
        "_pta009_reverted_resident", str(p)
    )
    spec = importlib.util.spec_from_loader(loader.name, loader)
    mod = importlib.util.module_from_spec(spec)
    # dataclass decorators resolve cls.__module__ through sys.modules
    sys.modules[loader.name] = mod
    try:
        loader.exec_module(mod)
    except BaseException:
        sys.modules.pop(loader.name, None)
        raise
    return mod


def _trace_express_step(mod):
    """Trace ``mod._express_step`` on tiny hand-rolled shapes with a
    LAUNDERING cost model — a where-mask at the model's output, the
    wrong site, exactly the shape that hid the original bug from a
    global-kill analysis."""
    Tp = Mp = 16
    kmax, pk, smax = 4, 2, 4
    dev = DenseInstance(
        c=np.full((Tp, Mp), 3, np.int32),
        u=np.full(Tp, 9, np.int32),
        w=np.full(Tp, 2, np.int32),
        dgen=np.ones(Mp, np.int32),
        s=np.ones(Mp, np.int32),
        task_valid=np.ones(Tp, bool),
        scale=np.int32(Tp + 1),
        cmax=np.int32(64),
        smax=smax,
    )
    neg1_t = np.full(Tp, -1, np.int32)
    dt = mod.DenseTopology(
        arc_unsched=neg1_t, arc_cluster=neg1_t, arc_u2s=neg1_t,
        arc_pref=np.full((Tp, pk), -1, np.int32),
        pref_machine=np.full((Tp, pk), -1, np.int32),
        pref_rack=np.full((Tp, pk), -1, np.int32),
        arc_c2m=np.full(Mp, -1, np.int32),
        arc_r2m=np.full(Mp, -1, np.int32),
        arc_m2s=np.full(Mp, -1, np.int32),
        rack_of=np.full(Mp, -1, np.int32),
        slots=np.ones(Mp, np.int32),
        n_tasks=np.int32(8),
    )
    cost_dev = np.zeros(64, np.int64)
    mini = np.zeros(3 * kmax + kmax * pk, np.int64)
    add_row = np.full(kmax, -1, np.int32)
    add_row[0] = Tp - 1
    add_pm = np.full((kmax, pk), -1, np.int32)
    add_pr = np.full((kmax, pk), -1, np.int32)
    zeros_t = np.zeros(Tp, np.int32)
    zeros_m = np.zeros(Mp, np.int32)
    model_fn = lambda mi: jnp.where(mi >= 0, mi, 0)  # noqa: E731
    with enable_x64(True):
        return jax.make_jaxpr(
            lambda dev, dt, cost, mini, a, l, f, ar, pm, pr:
            mod._express_step(
                dev, dt, cost, mini, a, l, f, ar, pm, pr,
                model_fn=model_fn, kmax=kmax, pk=pk, alpha=16,
                max_rounds=8, smax=smax, change_cap=4,
            )
        )(dev, dt, cost_dev, mini, zeros_t, zeros_t, zeros_m,
          add_row, add_pm, add_pr)


def _express_step_hits(closed):
    return [
        (c.primitive, c.function)
        for c in analyze_kernel("express", closed)
        if c.function == "_express_step"
    ]


class TestExpressAcceptance:
    def test_reverted_arrival_mask_fires(self, tmp_path):
        """Stripping PR 10's arrival-lane masks from the real
        _express_step source re-creates the shipped bug, and PTA009
        sees it: two unmasked tainted reduce_min folds."""
        mod = _load_reverted_resident(tmp_path)
        hits = _express_step_hits(_trace_express_step(mod))
        assert hits.count(("reduce_min", "_express_step")) == 2, hits

    def test_shipped_express_step_is_clean(self):
        """The same trace of the REAL module: the masks dominate, no
        _express_step candidate survives (the remaining candidates are
        the sanctioned solve-family folds)."""
        assert _express_step_hits(
            _trace_express_step(real_resident)
        ) == []

    def test_sanctioned_solve_family_sites_still_seen(self):
        """The sanctioned sites are FOUND by analyze_kernel (they are
        real tainted folds — safety is by table construction); the
        sanction list is what keeps them out of the violation stream.
        Guards against the pass silently going blind."""
        cands = [
            (c.primitive, c.function)
            for c in analyze_kernel(
                "express", _trace_express_step(real_resident)
            )
        ]
        assert ("reduce_min", "_task_options") in cands, cands
        assert ("reduce_sum", "_solve") in cands, cands
