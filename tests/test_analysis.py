"""Tests for the contract linter (poseidon_tpu/analysis).

Per-rule known-bad/known-good snippet pairs, the suppression contract
(a reason is mandatory), the self-check (the shipped tree is
violation-free), and the acceptance injections: seeding a ``.item()``
into the real ``ops/resident.py`` or an unlocked cross-thread mutation
into the real ``bridge/bridge.py`` must make the analyzer (and so CI)
fail.
"""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys
import textwrap

from poseidon_tpu.analysis import DEFAULT_CONTRACTS, analyze_tree
from poseidon_tpu.analysis.contracts import Contracts, ThreadContract

REPO = pathlib.Path(__file__).resolve().parent.parent


def run_on(tmp_path, files, contracts=DEFAULT_CONTRACTS):
    """Write a snippet tree under tmp_path and analyze it."""
    paths = []
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        if rel.endswith(".py"):
            paths.append(p)
    violations, _ = analyze_tree(tmp_path, paths, contracts)
    return violations


def codes(violations):
    return [v.code for v in violations]


class TestPTA001HostSync:
    # the suffix puts the snippet in a declared whole-file hot scope
    HOT = "poseidon_tpu/ops/resident.py"

    def test_bad_syncs_flagged(self, tmp_path):
        vs = run_on(tmp_path, {self.HOT: """\
            import jax
            import jax.numpy as jnp
            import numpy as np

            def round_step(x):
                v = x.item()
                h = np.asarray(x)
                g = jax.device_get(x)
                x.block_until_ready()
                cost = jnp.add(x, 1)
                s = int(cost)
                return v, h, g, s
        """})
        assert codes(vs) == ["PTA001"] * 5

    def test_good_host_code_clean(self, tmp_path):
        vs = run_on(tmp_path, {self.HOT: """\
            import numpy as np

            def round_step(asg_np, T):
                # int()/np ops on host data do not sync
                asg = np.where(asg_np >= 0, asg_np, -1)
                return int(T), asg
        """})
        assert vs == []

    def test_device_get_is_a_taint_barrier(self, tmp_path):
        vs = run_on(tmp_path, {self.HOT: """\
            import jax
            import jax.numpy as jnp

            def round_step(x):
                cost = jnp.add(x, 1)
                host = jax.device_get(cost)  # noqa: PTA001 -- test fixture: the sanctioned fetch
                return int(host)             # host data: no second sync
        """})
        assert vs == []

    def test_out_of_scope_file_not_checked(self, tmp_path):
        vs = run_on(tmp_path, {"poseidon_tpu/somewhere_else.py": """\
            def f(x):
                return x.item()
        """})
        assert vs == []


class TestPTA002ClusterLoops:
    BRIDGE = "poseidon_tpu/bridge/bridge.py"

    def test_loop_in_scope_flagged(self, tmp_path):
        vs = run_on(tmp_path, {self.BRIDGE: """\
            class SchedulerBridge:
                def begin_round(self):
                    n = 0
                    for t in self.tasks:
                        n += 1
                    return n
        """})
        assert codes(vs) == ["PTA002"]

    def test_genexp_over_cluster_flagged(self, tmp_path):
        vs = run_on(tmp_path, {self.BRIDGE: """\
            class SchedulerBridge:
                def begin_round(self, cluster):
                    return any(t.live for t in cluster.tasks)
        """})
        assert codes(vs) == ["PTA002"]

    def test_churn_loop_and_out_of_scope_clean(self, tmp_path):
        vs = run_on(tmp_path, {self.BRIDGE: """\
            class SchedulerBridge:
                def begin_round(self, dset):
                    for d in dset.place:   # O(churn): this round's deltas
                        self.apply(d)

                def observe_nodes(self, nodes):
                    for n in nodes:        # the poll path is O(cluster) by design
                        self.upsert(n)
        """})
        assert vs == []


class TestPTA003JitHygiene:
    def test_inline_jit_flagged(self, tmp_path):
        vs = run_on(tmp_path, {"poseidon_tpu/x.py": """\
            import jax

            def price(model, x):
                return jax.jit(model)(x)
        """})
        assert codes(vs) == ["PTA003"]

    def test_mutable_static_default_and_unknown_name(self, tmp_path):
        vs = run_on(tmp_path, {"poseidon_tpu/x.py": """\
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("opts", "zzz"))
            def f(x, opts=[]):
                return x
        """})
        assert sorted(codes(vs)) == ["PTA003", "PTA003"]
        msgs = " | ".join(v.message for v in vs)
        assert "mutable default" in msgs and "'zzz'" in msgs

    def test_nested_jit_closure_capture(self, tmp_path):
        vs = run_on(tmp_path, {"poseidon_tpu/x.py": """\
            import jax

            def outer(k):
                @jax.jit
                def inner(x):
                    return x + k
                return inner
        """})
        msgs = " | ".join(v.message for v in vs)
        assert codes(vs) == ["PTA003", "PTA003"]
        assert "defined inside a function" in msgs
        assert "closes over 'k'" in msgs

    def test_module_level_jit_clean(self, tmp_path):
        vs = run_on(tmp_path, {"poseidon_tpu/x.py": """\
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("n",))
            def f(x, n=4):
                return x * n

            _g = jax.jit(lambda x: x + 1)
        """})
        assert vs == []


class TestPTA004LockDiscipline:
    # SchedulerBridge is a declared thread class in the default contracts
    ANY = "poseidon_tpu/bridge/bridge.py"

    BAD = """\
        class SchedulerBridge:
            def __init__(self):
                self.round_num = 0

            def bump(self):
                self.round_num += 1

            def poll(self):  # pta: background-thread
                self.round_num += 1
    """

    def test_unlocked_cross_thread_write_flagged(self, tmp_path):
        vs = run_on(tmp_path, {self.ANY: self.BAD})
        # PTA004 flags both unlocked sites; the whole-program lockset
        # pass (PTA006) independently reports the attribute race
        assert set(codes(vs)) == {"PTA004", "PTA006"}
        assert codes(vs).count("PTA004") == 2
        assert codes(vs).count("PTA006") == 1

    def test_locked_sites_clean(self, tmp_path):
        vs = run_on(tmp_path, {self.ANY: """\
            class SchedulerBridge:
                def __init__(self):
                    self.round_num = 0

                def bump(self):
                    with self._lock:
                        self.round_num += 1

                def poll(self):  # pta: background-thread
                    with self._lock:
                        self.round_num += 1
        """})
        assert vs == []

    def test_declared_handoff_clean(self, tmp_path):
        contracts = Contracts(
            thread_classes={
                "SchedulerBridge": ThreadContract(
                    handoffs={"round_num": "test: monotonic counter"}
                ),
            },
        )
        vs = run_on(tmp_path, {self.ANY: self.BAD}, contracts)
        assert vs == []

    def test_single_thread_class_clean(self, tmp_path):
        vs = run_on(tmp_path, {self.ANY: """\
            class SchedulerBridge:
                def __init__(self):
                    self.round_num = 0

                def bump(self):
                    self.round_num += 1   # main thread only: fine
        """})
        assert vs == []


class TestPTA005Surface:
    def test_undeclared_and_dynamic_event_flagged(self, tmp_path):
        vs = run_on(tmp_path, {
            "poseidon_tpu/trace.py": """\
                EVENT_TYPES = frozenset({"ROUND", "SCHEDULE"})
            """,
            "poseidon_tpu/other.py": """\
                class T:
                    def go(self, name):
                        self.trace.emit("ROUND")
                        self.trace.emit("BOGUS")
                        self.trace.emit(name)
            """,
        })
        assert codes(vs) == ["PTA005", "PTA005"]
        msgs = " | ".join(v.message for v in vs)
        assert "BOGUS" in msgs and "dynamic" in msgs

    def test_missing_vocab_flagged(self, tmp_path):
        vs = run_on(tmp_path, {"poseidon_tpu/trace.py": """\
            def emit(x):
                pass
        """})
        assert codes(vs) == ["PTA005"]

    def test_undocumented_flag_flagged(self, tmp_path):
        files = {
            "poseidon_tpu/cli.py": """\
                import argparse

                def build_parser():
                    p = argparse.ArgumentParser()
                    p.add_argument("--alpha", type=int)
                    p.add_argument("--hidden", help=argparse.SUPPRESS)
                    return p
            """,
            "README.md": "docs mention --alpha here\n",
            "deploy/poseidon-tpu.cfg": "# no flags here\n",
        }
        vs = run_on(tmp_path, files)
        assert codes(vs) == ["PTA005"]
        assert "--alpha" in vs[0].message
        assert "deploy/poseidon-tpu.cfg" in vs[0].message
        # hidden (SUPPRESS) flags are exempt; documenting --alpha fixes it
        files["deploy/poseidon-tpu.cfg"] = "--alpha=3\n"
        assert run_on(tmp_path, files) == []

    def test_flag_name_prefix_does_not_count(self, tmp_path):
        # "--watch_max_lag" in a doc must NOT satisfy "--watch"
        vs = run_on(tmp_path, {
            "poseidon_tpu/cli.py": """\
                import argparse

                def build_parser():
                    p = argparse.ArgumentParser()
                    p.add_argument("--watch")
                    return p
            """,
            "README.md": "only --watch_max_lag is named\n",
            "deploy/poseidon-tpu.cfg": "--watch=false\n",
        })
        assert codes(vs) == ["PTA005"]
        assert "README.md" in vs[0].message


class TestPTA006LocksetRaces:
    """The whole-program lockset race detector (analysis/threads.py)."""

    ANY = "poseidon_tpu/pkg/mod.py"  # outside every PTA001/002 scope

    def test_spawn_site_inference_without_marker(self, tmp_path):
        """A Thread(target=self.m) spawn makes m background even with
        NO marker — the case PTA004's marker discipline cannot see."""
        vs = run_on(tmp_path, {self.ANY: """\
            import threading

            class Pump:
                def start(self):
                    self._t = threading.Thread(target=self._drain)
                    self._t.start()

                def _drain(self):
                    self.pending += 1

                def feed(self):
                    self.pending += 1
        """})
        assert codes(vs) == ["PTA006"]
        assert "Pump.pending" in vs[0].message
        assert "ThreadContract" in vs[0].message  # undeclared class

    def test_thread_subclass_run_is_background(self, tmp_path):
        vs = run_on(tmp_path, {self.ANY: """\
            import threading

            class Stream(threading.Thread):
                def run(self):
                    self.beat = 1.0

                def lag(self):
                    return self.beat
        """})
        # write on the reader thread, read on main, no lock, no handoff
        assert "beat" not in "".join(
            v.message for v in vs if v.code != "PTA006"
        )
        assert [v.code for v in vs] == ["PTA006"]

    def test_call_graph_closure_from_root(self, tmp_path):
        """An unmarked helper reached via self-calls from a background
        root inherits the background domain."""
        vs = run_on(tmp_path, {self.ANY: """\
            import threading

            class W:
                def go(self):
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    self._step()

                def _step(self):
                    self.count += 1

                def snapshot(self):
                    return self.count

                def reset(self):
                    self.count = 0
        """})
        assert [v.code for v in vs] == ["PTA006"]
        assert "W.count" in vs[0].message

    def test_wrapper_lambda_is_background(self, tmp_path):
        """A lambda handed to a declared spawn wrapper runs on its
        thread: touching self state from it is a cross-thread access."""
        vs = run_on(tmp_path, {self.ANY: """\
            from poseidon_tpu.ops.resident import _AsyncFetch

            class Solver:
                def dispatch(self):
                    self._warm = object()
                    return _AsyncFetch(lambda: self._warm)
        """})
        assert [v.code for v in vs] == ["PTA006"]
        assert "Solver._warm" in vs[0].message

    def test_cross_class_typed_access_seen(self, tmp_path):
        """The _WatchStream pattern: the owning class reads a stream
        attribute on the main thread through a typed container while
        the reader thread writes it."""
        vs = run_on(tmp_path, {self.ANY: """\
            import threading

            class Stream(threading.Thread):
                def run(self):
                    self.beat = 1.0

            class Owner:
                def __init__(self):
                    self._streams: dict[str, Stream] = {}

                def tick(self):
                    for name, s in self._streams.items():
                        if s.beat > 3:
                            return name
        """})
        assert [v.code for v in vs] == ["PTA006"]
        assert "Stream.beat" in vs[0].message

    def test_wrapper_lambda_in_init_not_exempt(self, tmp_path):
        """__init__'s construction exemption must not cover a
        background context __init__ itself creates: a state-touching
        lambda handed to a spawn wrapper races every later main-thread
        access (review regression)."""
        vs = run_on(tmp_path, {self.ANY: """\
            from poseidon_tpu.ops.resident import _AsyncFetch

            class Solver:
                def __init__(self):
                    self._warm = None
                    self._f = _AsyncFetch(lambda: self._warm)

                def finish(self):
                    self._warm = object()
        """})
        assert [v.code for v in vs] == ["PTA006"]
        assert "Solver._warm" in vs[0].message

    def test_common_lock_clean(self, tmp_path):
        vs = run_on(tmp_path, {self.ANY: """\
            import threading

            class Pump:
                def start(self):
                    threading.Thread(target=self._drain).start()

                def _drain(self):
                    with self._lock:
                        self.pending += 1

                def feed(self):
                    with self._lock:
                        self.pending += 1
        """})
        assert vs == []

    def test_subscript_store_counts_as_write(self, tmp_path):
        """``self.d[k] = v`` mutates the mapping: a write for race
        purposes (the metrics-registry pattern)."""
        vs = run_on(tmp_path, {self.ANY: """\
            import threading

            class Registry:
                def register(self, k, v):
                    self._metrics[k] = v

                def render(self):  # pta: background-thread
                    return list(self._metrics)
        """})
        assert [v.code for v in vs] == ["PTA006"]
        assert "Registry._metrics" in vs[0].message

    def test_stale_handoff_flagged(self, tmp_path):
        from poseidon_tpu.analysis.contracts import (
            Contracts,
            ThreadContract,
        )

        contracts = Contracts(
            thread_classes={
                "Pump": ThreadContract(handoffs={
                    "ghost": "supposedly cross-thread",
                }),
            },
        )
        vs = run_on(tmp_path, {self.ANY: """\
            class Pump:
                def feed(self):
                    self.pending = 1
        """}, contracts)
        assert [v.code for v in vs] == ["PTA006"]
        assert "stale handoff" in vs[0].message
        assert "ghost" in vs[0].message

    def test_tests_dir_is_not_race_evidence(self, tmp_path):
        """Evidence scoping: a test poking privates on the main thread
        must neither fabricate a race nor keep a stale handoff alive —
        tests/ files are excluded from the access map entirely."""
        from poseidon_tpu.analysis.contracts import (
            Contracts,
            ThreadContract,
        )

        files = {
            self.ANY: """\
                import threading

                class Pump(threading.Thread):
                    def run(self):
                        self.beat = 1.0
            """,
            # the ONLY main-thread accessor lives in a test file
            "tests/test_pump.py": """\
                def test_poke(p: "Pump"):
                    assert p.beat > 0
            """,
        }
        contracts = Contracts(
            thread_classes={
                "Pump": ThreadContract(handoffs={
                    "beat": "claimed cross-thread (only a test reads it)",
                }),
            },
            path_rules=(("tests/", ("PTA000", "PTA003", "PTA005")),),
        )
        vs = run_on(tmp_path, files, contracts)
        # the handoff is STALE: production code never reads beat on
        # the main thread, and the test's read is not evidence
        assert [v.code for v in vs] == ["PTA006"]
        assert "stale handoff" in vs[0].message

    def test_live_handoff_not_stale(self, tmp_path):
        from poseidon_tpu.analysis.contracts import (
            Contracts,
            ThreadContract,
        )

        contracts = Contracts(
            thread_classes={
                "Pump": ThreadContract(handoffs={
                    "value": "written before the Event set",
                }),
            },
        )
        vs = run_on(tmp_path, {self.ANY: """\
            class Pump:
                def run(self):  # pta: background-thread
                    self.value = 42

                def result(self):
                    return self.value
        """}, contracts)
        assert vs == []


class TestPTA006Acceptance:
    """Negative injections against the REAL tree: removing any declared
    handoff or lock acquisition must make the linter fire (mirrors
    PR 5's .item()-injection acceptance)."""

    @staticmethod
    def _without_handoff(cls, attr):
        import dataclasses

        from poseidon_tpu.analysis.contracts import (
            DEFAULT_CONTRACTS,
            ThreadContract,
        )

        tc = DEFAULT_CONTRACTS.thread_classes[cls]
        h = dict(tc.handoffs)
        h.pop(attr)
        classes = dict(DEFAULT_CONTRACTS.thread_classes)
        classes[cls] = ThreadContract(lock_attr=tc.lock_attr, handoffs=h)
        return dataclasses.replace(
            DEFAULT_CONTRACTS, thread_classes=classes
        )

    def test_every_declared_handoff_is_load_bearing(self):
        """Removing ANY handoff entry from contracts.py fires PTA006 on
        the shipped tree — the allowlist holds no dead weight."""
        from poseidon_tpu.analysis.contracts import DEFAULT_CONTRACTS

        checked = 0
        for cls, tc in DEFAULT_CONTRACTS.thread_classes.items():
            for attr in tc.handoffs:
                vs, _ = analyze_tree(
                    REPO, contracts=self._without_handoff(cls, attr)
                )
                hits = [
                    v for v in vs
                    if v.code == "PTA006" and f"{cls}.{attr}" in v.message
                ]
                assert hits, f"dropping {cls}.{attr} went undetected"
                checked += 1
        assert checked >= 5  # _AsyncFetch x2 + _WatchStream x3

    def test_removing_lock_acquisition_in_obs_fires(self, tmp_path):
        """Stripping the registry lock from render() (the metrics
        server's handler-thread entry) fires PTA006."""
        src = (REPO / "poseidon_tpu/obs/metrics.py").read_text()
        anchor = "        out: list[str] = []\n        with self._lock:"
        assert anchor in src
        bad = src.replace(
            anchor,
            "        out: list[str] = []\n        if True:",
            1,
        )
        vs = run_on(tmp_path, {"poseidon_tpu/obs/metrics.py": bad})
        assert any(
            v.code == "PTA006" and "MetricsRegistry._metrics" in v.message
            for v in vs
        ), [v.message for v in vs]

    def test_removing_lock_acquisition_in_health_latch_fires(
        self, tmp_path
    ):
        src = (REPO / "poseidon_tpu/obs/server.py").read_text()
        anchor = "        with self._lock:\n            self._round_done"
        assert anchor in src
        bad = src.replace(
            anchor,
            "        if True:\n            self._round_done",
            1,
        )
        vs = run_on(tmp_path, {"poseidon_tpu/obs/server.py": bad})
        assert any(
            v.code == "PTA006" and "HealthState._round_done" in v.message
            for v in vs
        ), [v.message for v in vs]

    def test_unmarked_spawn_injection_in_bridge_fails(self, tmp_path):
        """An UNMARKED background mutation — spawn-site inference only,
        PTA004's marker discipline is blind to it — still fails CI."""
        src = (REPO / "poseidon_tpu/bridge/bridge.py").read_text()
        anchor = "    def cancel_round("
        assert anchor in src
        bad = src.replace(anchor, (
            "    def _spawn_refresher(self):\n"
            "        threading.Thread(target=self._bg_refresh).start()\n"
            "\n"
            "    def _bg_refresh(self):\n"
            "        self.round_num += 1\n\n"
        ) + anchor, 1)
        vs = run_on(tmp_path, {"poseidon_tpu/bridge/bridge.py": bad})
        assert not any(
            v.code == "PTA004" and "round_num" in v.message for v in vs
        )  # no marker: the file-local rule cannot see it
        assert any(
            v.code == "PTA006" and "round_num" in v.message for v in vs
        ), [v.message for v in vs]

    def test_wrapper_lambda_injection_in_resident_fails(self, tmp_path):
        """A lambda smuggled into _AsyncFetch that touches solver state
        is a background access and fails CI."""
        src = (REPO / "poseidon_tpu/ops/resident.py").read_text()
        anchor = "        self._inflight = True"
        assert anchor in src
        bad = src.replace(
            anchor,
            "        _probe = _AsyncFetch(lambda: self._warm)\n"
            + anchor, 1,
        )
        vs = run_on(tmp_path, {"poseidon_tpu/ops/resident.py": bad})
        assert any(
            v.code == "PTA006" and "ResidentSolver._warm" in v.message
            for v in vs
        ), [v.message for v in vs]

    def test_wrapper_lambda_injection_in_service_fails(self, tmp_path):
        """The service lane: a chunk-fetch lambda reaching back into
        dispatcher state races the pump thread's bookkeeping."""
        src = (REPO / "poseidon_tpu/service/dispatch.py").read_text()
        anchor = "        chunk.future = _AsyncFetch(_fetch)"
        assert anchor in src
        bad = src.replace(
            anchor,
            "        chunk.future = _AsyncFetch("
            "lambda: (_fetch(), self.dispatches))",
            1,
        )
        vs = run_on(tmp_path, {"poseidon_tpu/service/dispatch.py": bad})
        assert any(
            v.code == "PTA006"
            and "BatchDispatcher.dispatches" in v.message
            for v in vs
        ), [v.message for v in vs]


class TestPTA007RecompileHazard:
    ANY = "poseidon_tpu/pkg/mod.py"

    # closing quotes at column 0: an indented close would leave
    # trailing spaces that merge into the appended snippet's first line
    KERNEL = """\
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("smax", "n_prefs"))
        def kern(x, smax, n_prefs):
            return x

"""

    def test_unfloored_static_flagged(self, tmp_path):
        vs = run_on(tmp_path, {self.ANY: self.KERNEL + """\
        def round(dev, topo):
            smax = max(int(topo.slots_max), 1)
            return kern(dev, smax=smax, n_prefs=2)
        """})
        assert codes(vs) == ["PTA007"]
        assert "'smax'" in vs[0].message

    def test_floored_static_clean(self, tmp_path):
        vs = run_on(tmp_path, {self.ANY: self.KERNEL + """\
        def round(self, dev, topo):
            self._s_floor = max(int(topo.slots_max), self._s_floor)
            smax = self._s_floor
            return kern(dev, smax=smax, n_prefs=2)
        """})
        assert vs == []

    def test_reassignment_clears_taint_flow_ordered(self, tmp_path):
        """A sink BETWEEN the hazard and the floored re-binding fires;
        the same sink after the re-binding is clean."""
        vs = run_on(tmp_path, {self.ANY: self.KERNEL + """\
        def round(self, dev, topo):
            p = topo.max_prefs
            early = kern(dev, smax=4, n_prefs=p)
            p = self._p_floor
            late = kern(dev, smax=4, n_prefs=p)
            return early, late
        """})
        assert codes(vs) == ["PTA007"]
        assert "'n_prefs'" in vs[0].message

    def test_pad_sink_flagged(self, tmp_path):
        vs = run_on(tmp_path, {self.ANY: """\
        from poseidon_tpu.graph.network import pad_bucket

        def prep(E, meta, build_cost_inputs_host):
            t = pad_bucket(max(len(meta.task_uids), 1))
            return build_cost_inputs_host(E, meta, t_min=t)
        """})
        assert codes(vs) == ["PTA007"]
        assert "'t_min'" in vs[0].message

    def test_same_name_jit_defs_do_not_shadow(self, tmp_path):
        """A tests/ (or any second) jitted def reusing a production
        kernel's name must not replace its static-param signature in
        the registry: ambiguous names are dropped, and tests/ never
        feeds the registry at all (review regression)."""
        vs = run_on(tmp_path, {
            self.ANY: self.KERNEL + """\
        def round(dev, topo):
            smax = max(int(topo.slots_max), 1)
            return kern(dev, smax=smax, n_prefs=2)
        """,
            # same name, different statics — in a NON-enforcing dir
            "tests/test_shadow.py": """\
                import jax
                from functools import partial

                @partial(jax.jit, static_argnames=("other",))
                def kern(x, other):
                    return x
            """,
        })
        # the production hazard still fires against the REAL signature
        assert codes(vs) == ["PTA007"]
        assert "'smax'" in vs[0].message

    def test_acceptance_reverted_pr8_smax_floor(self, tmp_path):
        """Reverting PR 8's smax grow-only floor in the REAL resident
        solver (static smax follows shrinking max-free-seats again)
        fails CI."""
        src = (REPO / "poseidon_tpu/ops/resident.py").read_text()
        floored = (
            "        self._s_floor = pad_bucket(\n"
            "            max(int(topo.slots.max(initial=1)), 1),\n"
            "            minimum=self._s_floor,\n"
            "        )\n"
            "        smax = min(self._s_floor, "
            "dt_host.arc_unsched.shape[0])"
        )
        assert floored in src
        bad = src.replace(
            floored,
            "        smax = max(int(topo.slots.max(initial=1)), 1)",
            1,
        )
        vs = run_on(tmp_path, {"poseidon_tpu/ops/resident.py": bad})
        hits = [
            v for v in vs
            if v.code == "PTA007" and "'smax'" in v.message
            and "_resident_chain" in v.message
        ]
        assert hits, [v.message for v in vs]

    def test_unfloored_pref_width_reverted(self, tmp_path):
        """Reverting the pref-width floor (n_prefs follows the live
        max_prefs again) fails CI — PR 8's second recompile source."""
        src = (REPO / "poseidon_tpu/ops/resident.py").read_text()
        floored = "        self._p_floor = max(topo.max_prefs, " \
                  "self._p_floor)\n        P = self._p_floor"
        assert floored in src
        bad = src.replace(
            floored, "        P = topo.max_prefs", 1
        )
        vs = run_on(tmp_path, {"poseidon_tpu/ops/resident.py": bad})
        hits = [
            v for v in vs
            if v.code == "PTA007" and "'n_prefs'" in v.message
        ]
        assert hits, [v.message for v in vs]


class TestSuppressions:
    HOT = "poseidon_tpu/ops/resident.py"

    def test_suppression_without_reason_fails(self, tmp_path):
        vs = run_on(tmp_path, {self.HOT: """\
            def f(x):
                return x.item()  # noqa: PTA001
        """})
        # the bare suppression is PTA000 AND suppresses nothing
        assert codes(vs) == ["PTA000", "PTA001"]

    def test_suppression_with_reason_suppresses(self, tmp_path):
        vs = run_on(tmp_path, {self.HOT: """\
            def f(x):
                return x.item()  # noqa: PTA001 -- test fixture: sanctioned
        """})
        assert vs == []

    def test_suppression_only_covers_named_code(self, tmp_path):
        vs = run_on(tmp_path, {self.HOT: """\
            def f(x):
                return x.item()  # noqa: PTA002 -- wrong code named
        """})
        assert codes(vs) == ["PTA001"]


class TestSuppressionSpans:
    """Satellite fix: a suppression covers its whole statement-header
    span, not just its literal line (regression: a noqa on a decorated
    def did not cover violations reported on the decorator line, and
    vice versa)."""

    def test_noqa_on_def_covers_decorator_violation(self, tmp_path):
        # the unknown-static-name violation anchors on the decorator's
        # tuple element line, one line ABOVE the def carrying the noqa
        vs = run_on(tmp_path, {"poseidon_tpu/x.py": """\
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("zzz",))
            def f(x):  # noqa: PTA003 -- test fixture: span regression
                return x
        """})
        assert vs == []

    def test_noqa_on_decorator_covers_def_violation(self, tmp_path):
        # nested-jit violations anchor on the DEF line; the noqa sits
        # on the decorator line above it
        vs = run_on(tmp_path, {"poseidon_tpu/x.py": """\
            import jax

            def outer(k):
                @jax.jit  # noqa: PTA003 -- test fixture: span regression
                def inner(x):
                    return x + k
                return inner
        """})
        assert vs == []

    def test_noqa_covers_multiline_statement(self, tmp_path):
        # violation anchors on the call's first line; the noqa sits on
        # a LATER line of the same multi-line statement
        vs = run_on(tmp_path, {"poseidon_tpu/ops/resident.py": """\
            def f(x, g):
                v = g(
                    x.item(),
                )  # noqa: PTA001 -- test fixture: same-statement span
                return v
        """})
        assert vs == []

    def test_noqa_on_with_header_does_not_blanket_body(self, tmp_path):
        # compound statements expose only their HEADER as the span: a
        # noqa on the with-line must not suppress the block under it
        vs = run_on(tmp_path, {"poseidon_tpu/ops/resident.py": """\
            def f(x, lock):
                with lock:  # noqa: PTA001 -- test fixture: header only
                    return x.item()
        """})
        assert codes(vs) == ["PTA001"]


class TestSuppressionAudit:
    """Satellite: --audit-suppressions reports dead noqas (a reasoned
    suppression whose rule no longer fires on that statement)."""

    HOT = "poseidon_tpu/ops/resident.py"

    def run_audit(self, tmp_path, files):
        import poseidon_tpu.analysis.core as core

        paths = []
        for rel, src in files.items():
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(textwrap.dedent(src))
            paths.append(p)
        vs, _ = core.audit_suppressions(tmp_path, paths)
        return vs

    def test_dead_suppression_reported(self, tmp_path):
        vs = self.run_audit(tmp_path, {self.HOT: """\
            def f(x):
                return x + 1  # noqa: PTA001 -- nothing syncs here any more
        """})
        assert [v.rule for v in vs] == ["dead-suppression"]
        assert "PTA001" in vs[0].message

    def test_live_suppression_not_reported(self, tmp_path):
        vs = self.run_audit(tmp_path, {self.HOT: """\
            def f(x):
                return x.item()  # noqa: PTA001 -- sanctioned fixture
        """})
        assert vs == []

    def test_partially_dead_multi_code_noqa(self, tmp_path):
        # PTA001 fires (live) but PTA002 never can here (dead half)
        vs = self.run_audit(tmp_path, {self.HOT: """\
            def f(x):
                return x.item()  # noqa: PTA001,PTA002 -- half-stale fixture
        """})
        assert [v.rule for v in vs] == ["dead-suppression"]
        assert "PTA002" in vs[0].message

    def test_bare_noqa_not_audited(self, tmp_path):
        # a reasonless suppression is already PTA000 in the main pass
        # and suppresses nothing — the audit does not double-report it
        vs = self.run_audit(tmp_path, {self.HOT: """\
            def f(x):
                return x + 1  # noqa: PTA001
        """})
        assert vs == []

    def test_shipped_tree_audit_clean(self):
        from poseidon_tpu.analysis.core import audit_suppressions

        vs, files = audit_suppressions(REPO)
        assert files > 30
        assert vs == [], "\n".join(
            f"{v.path}:{v.line} {v.message}" for v in vs
        )

    def test_cli_flag_fails_on_dead_noqa(self, tmp_path):
        bad = tmp_path / "poseidon_tpu" / "ops" / "resident.py"
        bad.parent.mkdir(parents=True)
        bad.write_text(
            "def f(x):\n"
            "    return x + 1  # noqa: PTA001 -- stale reason\n"
        )
        proc = subprocess.run(
            [sys.executable, "-m", "poseidon_tpu.analysis",
             "--format=json", "--audit-suppressions",
             "--root", str(tmp_path), str(bad)],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["count"] == 1
        assert doc["violations"][0]["rule"] == "dead-suppression"


class TestWidenedTargets:
    """Satellite: tests/ is scanned under a per-rule scope — jit
    hygiene (PTA003) and surface vocabulary (PTA005) apply there, the
    hot-path/thread rules do not (test files deliberately contain
    seeded violations as data)."""

    def test_default_targets_include_tests(self):
        from poseidon_tpu.analysis import default_targets

        rels = {
            p.relative_to(REPO).as_posix() for p in default_targets(REPO)
        }
        assert "tests/test_analysis.py" in rels
        assert "bench.py" in rels

    def test_jit_hygiene_applies_in_tests_dir(self, tmp_path):
        vs = run_on(tmp_path, {"tests/test_x.py": """\
            import jax

            def test_something(model, x):
                return jax.jit(model)(x)
        """})
        assert codes(vs) == ["PTA003"]
        assert vs[0].path == "tests/test_x.py"

    def test_hot_path_rules_do_not_apply_in_tests_dir(self, tmp_path):
        # the same .item() that fails in ops/resident.py is test data
        # under tests/ — only the scoped rules run there
        vs = run_on(tmp_path, {"tests/test_x.py": """\
            def test_something(x):
                return x.item()
        """})
        assert vs == []

    def test_suppression_hygiene_still_applies_in_tests_dir(
        self, tmp_path
    ):
        vs = run_on(tmp_path, {"tests/test_x.py": """\
            import jax

            def test_something(model, x):
                return jax.jit(model)(x)  # noqa: PTA003
        """})
        # the bare suppression is PTA000 AND suppresses nothing
        assert codes(vs) == ["PTA000", "PTA003"]


class TestJsonSchema:
    """Satellite: the CLI's JSON document is load-bearing for CI and
    downstream tooling — field names, violation ordering, and exit
    codes are locked here."""

    VIOLATION_KEYS = ["code", "rule", "path", "line", "col", "message"]

    def run_cli(self, tmp_path, files, *extra):
        paths = []
        for rel, src in files.items():
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(textwrap.dedent(src))
            paths.append(str(p))
        proc = subprocess.run(
            [sys.executable, "-m", "poseidon_tpu.analysis",
             "--format=json", "--root", str(tmp_path), *extra, *paths],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        return proc, json.loads(proc.stdout) if proc.stdout else None

    def test_clean_tree_schema_and_exit_zero(self, tmp_path):
        proc, doc = self.run_cli(
            tmp_path, {"poseidon_tpu/x.py": "A = 1\n"}
        )
        assert proc.returncode == 0
        assert sorted(doc) == ["count", "files_scanned", "violations"]
        assert doc == {
            "violations": [], "count": 0, "files_scanned": 1,
        }

    def test_dirty_tree_schema_ordering_and_exit_one(self, tmp_path):
        proc, doc = self.run_cli(tmp_path, {
            "poseidon_tpu/ops/resident.py": """\
                def b(x):
                    return x.item()

                def a(x):
                    h = x.item()
                    return int(h), x.block_until_ready()
            """,
            "poseidon_tpu/a_first.py": """\
                import jax

                def f(model, x):
                    return jax.jit(model)(x)
            """,
        })
        assert proc.returncode == 1
        assert doc["count"] == len(doc["violations"]) == 4
        for v in doc["violations"]:
            assert list(v) == self.VIOLATION_KEYS
            assert isinstance(v["line"], int)
            assert isinstance(v["col"], int)
        keys = [
            (v["path"], v["line"], v["col"], v["code"])
            for v in doc["violations"]
        ]
        assert keys == sorted(keys), "violations must be sorted"
        # path ordering puts a_first.py's PTA003 before resident.py
        assert doc["violations"][0]["path"].endswith("a_first.py")

    def test_fully_suppressed_tree_counts_zero_exit_zero(self, tmp_path):
        proc, doc = self.run_cli(tmp_path, {
            "poseidon_tpu/ops/resident.py": """\
                def f(x):
                    return x.item()  # noqa: PTA001 -- schema fixture
            """,
        })
        assert proc.returncode == 0
        assert doc == {
            "violations": [], "count": 0, "files_scanned": 1,
        }

    def test_kernels_audited_key_only_with_jaxpr(self, tmp_path):
        # without --jaxpr the key is absent (checked via clean run
        # above); the jaxpr lane's schema is asserted in
        # tests/test_jaxpr_check.py where the trace cost is paid once
        proc, doc = self.run_cli(
            tmp_path, {"poseidon_tpu/x.py": "A = 1\n"}
        )
        assert "kernels_audited" not in doc


class TestSelfCheck:
    def test_shipped_tree_is_violation_free(self):
        violations, files_scanned = analyze_tree(REPO)
        assert files_scanned > 30
        assert violations == [], "\n".join(
            f"{v.path}:{v.line} {v.code} {v.message}" for v in violations
        )

    def test_injected_item_in_resident_fused_round_fails(self, tmp_path):
        """Acceptance: a stray .item() in the resident round fails CI."""
        src = (REPO / "poseidon_tpu/ops/resident.py").read_text()
        anchor = "        self._warm = state"
        assert anchor in src
        bad = src.replace(
            anchor, "        leak = primal.item()\n" + anchor, 1
        )
        vs = run_on(tmp_path, {"poseidon_tpu/ops/resident.py": bad})
        assert any(
            v.code == "PTA001" and ".item()" in v.message for v in vs
        )

    def test_injected_unlocked_mutation_in_bridge_fails(self, tmp_path):
        """Acceptance: an unlocked cross-thread mutation in the bridge
        fails CI."""
        src = (REPO / "poseidon_tpu/bridge/bridge.py").read_text()
        anchor = "    def cancel_round("
        assert anchor in src
        bad = src.replace(anchor, (
            "    def _bg_refresh(self):  # pta: background-thread\n"
            "        self.round_num += 1\n\n"
        ) + anchor, 1)
        vs = run_on(tmp_path, {"poseidon_tpu/bridge/bridge.py": bad})
        assert any(
            v.code == "PTA004" and "round_num" in v.message for v in vs
        )

    def test_unmodified_copies_stay_clean(self, tmp_path):
        """The injection tests prove the analyzer reacts to the SEED,
        not to analyzing a file in isolation."""
        vs = run_on(tmp_path, {
            "poseidon_tpu/ops/resident.py":
                (REPO / "poseidon_tpu/ops/resident.py").read_text(),
            "poseidon_tpu/bridge/bridge.py":
                (REPO / "poseidon_tpu/bridge/bridge.py").read_text(),
        })
        assert vs == []


class TestCli:
    def test_json_output_clean_exit_zero(self):
        proc = subprocess.run(
            [sys.executable, "-m", "poseidon_tpu.analysis",
             "--format=json"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["count"] == 0
        assert doc["violations"] == []
        assert doc["files_scanned"] > 30

    def test_analyze_file_api_in_fresh_interpreter(self, tmp_path):
        """Regression: the public analyze_file must load the rule
        registry itself — a fresh interpreter using only analyze_file
        must not report a violating file as clean."""
        bad = tmp_path / "poseidon_tpu" / "ops" / "resident.py"
        bad.parent.mkdir(parents=True)
        bad.write_text("def f(x):\n    return x.item()\n")
        proc = subprocess.run(
            [sys.executable, "-c", (
                "import pathlib, sys\n"
                "from poseidon_tpu.analysis import analyze_file\n"
                f"vs = analyze_file(pathlib.Path({str(bad)!r}), "
                f"pathlib.Path({str(tmp_path)!r}))\n"
                "assert [v.code for v in vs] == ['PTA001'], vs\n"
                "print('ok')\n"
            )],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_path_outside_root_exits_two(self, tmp_path):
        stray = tmp_path / "stray.py"
        stray.write_text("x = 1\n")
        proc = subprocess.run(
            [sys.executable, "-m", "poseidon_tpu.analysis",
             "--root", str(REPO / "poseidon_tpu"), str(stray)],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "outside --root" in proc.stderr

    def test_violations_exit_one(self, tmp_path):
        bad = tmp_path / "poseidon_tpu" / "ops" / "resident.py"
        bad.parent.mkdir(parents=True)
        bad.write_text("def f(x):\n    return x.item()\n")
        proc = subprocess.run(
            [sys.executable, "-m", "poseidon_tpu.analysis",
             "--format=json", "--root", str(tmp_path), str(bad)],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["count"] == 1
        assert doc["violations"][0]["code"] == "PTA001"


class TestRuleFilter:
    """Satellite: ``--rule PTA0NN[,PTA0MM]`` isolates one analysis —
    the CI lanes run PTA010 and PTA008,PTA009 in isolation, and
    bisecting a red full run needs per-rule reruns."""

    DIRTY = {
        # one PTA001 (hot-path sync) + one PTA003 (inline jit)
        "poseidon_tpu/ops/resident.py": """\
            def f(x):
                return x.item()
        """,
        "poseidon_tpu/misc.py": """\
            import jax

            def g(model, x):
                return jax.jit(model)(x)
        """,
    }

    def run_cli(self, tmp_path, files, *extra):
        paths = []
        for rel, src in files.items():
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(textwrap.dedent(src))
            paths.append(str(p))
        proc = subprocess.run(
            [sys.executable, "-m", "poseidon_tpu.analysis",
             "--format=json", "--root", str(tmp_path), *extra, *paths],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        return proc, json.loads(proc.stdout) if proc.stdout else None

    def test_single_rule_filters_other_findings(self, tmp_path):
        proc, doc = self.run_cli(tmp_path, self.DIRTY)
        assert proc.returncode == 1
        assert sorted(v["code"] for v in doc["violations"]) == \
            ["PTA001", "PTA003"]
        proc, doc = self.run_cli(
            tmp_path, self.DIRTY, "--rule", "PTA001"
        )
        assert proc.returncode == 1
        assert [v["code"] for v in doc["violations"]] == ["PTA001"]

    def test_comma_list_selects_both(self, tmp_path):
        proc, doc = self.run_cli(
            tmp_path, self.DIRTY, "--rule", "PTA001,PTA003"
        )
        assert proc.returncode == 1
        assert sorted(v["code"] for v in doc["violations"]) == \
            ["PTA001", "PTA003"]

    def test_selected_rule_clean_exits_zero(self, tmp_path):
        proc, doc = self.run_cli(
            tmp_path, self.DIRTY, "--rule", "PTA010"
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert doc["violations"] == []

    def test_unknown_rule_exits_two(self, tmp_path):
        proc, _ = self.run_cli(
            tmp_path, self.DIRTY, "--rule", "PTA099"
        )
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "unknown rule id" in proc.stderr
        assert "PTA099" in proc.stderr

    def test_no_python_targets_exits_two(self, tmp_path):
        sub = tmp_path / "poseidon_tpu" / "empty"
        sub.mkdir(parents=True)
        (sub / "notes.md").write_text("no code here\n")
        proc = subprocess.run(
            [sys.executable, "-m", "poseidon_tpu.analysis",
             "--root", str(tmp_path), str(sub)],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "no Python targets" in proc.stderr
