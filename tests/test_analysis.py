"""Tests for the contract linter (poseidon_tpu/analysis).

Per-rule known-bad/known-good snippet pairs, the suppression contract
(a reason is mandatory), the self-check (the shipped tree is
violation-free), and the acceptance injections: seeding a ``.item()``
into the real ``ops/resident.py`` or an unlocked cross-thread mutation
into the real ``bridge/bridge.py`` must make the analyzer (and so CI)
fail.
"""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys
import textwrap

from poseidon_tpu.analysis import DEFAULT_CONTRACTS, analyze_tree
from poseidon_tpu.analysis.contracts import Contracts, ThreadContract

REPO = pathlib.Path(__file__).resolve().parent.parent


def run_on(tmp_path, files, contracts=DEFAULT_CONTRACTS):
    """Write a snippet tree under tmp_path and analyze it."""
    paths = []
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        if rel.endswith(".py"):
            paths.append(p)
    violations, _ = analyze_tree(tmp_path, paths, contracts)
    return violations


def codes(violations):
    return [v.code for v in violations]


class TestPTA001HostSync:
    # the suffix puts the snippet in a declared whole-file hot scope
    HOT = "poseidon_tpu/ops/resident.py"

    def test_bad_syncs_flagged(self, tmp_path):
        vs = run_on(tmp_path, {self.HOT: """\
            import jax
            import jax.numpy as jnp
            import numpy as np

            def round_step(x):
                v = x.item()
                h = np.asarray(x)
                g = jax.device_get(x)
                x.block_until_ready()
                cost = jnp.add(x, 1)
                s = int(cost)
                return v, h, g, s
        """})
        assert codes(vs) == ["PTA001"] * 5

    def test_good_host_code_clean(self, tmp_path):
        vs = run_on(tmp_path, {self.HOT: """\
            import numpy as np

            def round_step(asg_np, T):
                # int()/np ops on host data do not sync
                asg = np.where(asg_np >= 0, asg_np, -1)
                return int(T), asg
        """})
        assert vs == []

    def test_device_get_is_a_taint_barrier(self, tmp_path):
        vs = run_on(tmp_path, {self.HOT: """\
            import jax
            import jax.numpy as jnp

            def round_step(x):
                cost = jnp.add(x, 1)
                host = jax.device_get(cost)  # noqa: PTA001 -- test fixture: the sanctioned fetch
                return int(host)             # host data: no second sync
        """})
        assert vs == []

    def test_out_of_scope_file_not_checked(self, tmp_path):
        vs = run_on(tmp_path, {"poseidon_tpu/somewhere_else.py": """\
            def f(x):
                return x.item()
        """})
        assert vs == []


class TestPTA002ClusterLoops:
    BRIDGE = "poseidon_tpu/bridge/bridge.py"

    def test_loop_in_scope_flagged(self, tmp_path):
        vs = run_on(tmp_path, {self.BRIDGE: """\
            class SchedulerBridge:
                def begin_round(self):
                    n = 0
                    for t in self.tasks:
                        n += 1
                    return n
        """})
        assert codes(vs) == ["PTA002"]

    def test_genexp_over_cluster_flagged(self, tmp_path):
        vs = run_on(tmp_path, {self.BRIDGE: """\
            class SchedulerBridge:
                def begin_round(self, cluster):
                    return any(t.live for t in cluster.tasks)
        """})
        assert codes(vs) == ["PTA002"]

    def test_churn_loop_and_out_of_scope_clean(self, tmp_path):
        vs = run_on(tmp_path, {self.BRIDGE: """\
            class SchedulerBridge:
                def begin_round(self, dset):
                    for d in dset.place:   # O(churn): this round's deltas
                        self.apply(d)

                def observe_nodes(self, nodes):
                    for n in nodes:        # the poll path is O(cluster) by design
                        self.upsert(n)
        """})
        assert vs == []


class TestPTA003JitHygiene:
    def test_inline_jit_flagged(self, tmp_path):
        vs = run_on(tmp_path, {"poseidon_tpu/x.py": """\
            import jax

            def price(model, x):
                return jax.jit(model)(x)
        """})
        assert codes(vs) == ["PTA003"]

    def test_mutable_static_default_and_unknown_name(self, tmp_path):
        vs = run_on(tmp_path, {"poseidon_tpu/x.py": """\
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("opts", "zzz"))
            def f(x, opts=[]):
                return x
        """})
        assert sorted(codes(vs)) == ["PTA003", "PTA003"]
        msgs = " | ".join(v.message for v in vs)
        assert "mutable default" in msgs and "'zzz'" in msgs

    def test_nested_jit_closure_capture(self, tmp_path):
        vs = run_on(tmp_path, {"poseidon_tpu/x.py": """\
            import jax

            def outer(k):
                @jax.jit
                def inner(x):
                    return x + k
                return inner
        """})
        msgs = " | ".join(v.message for v in vs)
        assert codes(vs) == ["PTA003", "PTA003"]
        assert "defined inside a function" in msgs
        assert "closes over 'k'" in msgs

    def test_module_level_jit_clean(self, tmp_path):
        vs = run_on(tmp_path, {"poseidon_tpu/x.py": """\
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("n",))
            def f(x, n=4):
                return x * n

            _g = jax.jit(lambda x: x + 1)
        """})
        assert vs == []


class TestPTA004LockDiscipline:
    # SchedulerBridge is a declared thread class in the default contracts
    ANY = "poseidon_tpu/bridge/bridge.py"

    BAD = """\
        class SchedulerBridge:
            def __init__(self):
                self.round_num = 0

            def bump(self):
                self.round_num += 1

            def poll(self):  # pta: background-thread
                self.round_num += 1
    """

    def test_unlocked_cross_thread_write_flagged(self, tmp_path):
        vs = run_on(tmp_path, {self.ANY: self.BAD})
        assert set(codes(vs)) == {"PTA004"}
        assert len(vs) == 2  # both unlocked sites (main + background)

    def test_locked_sites_clean(self, tmp_path):
        vs = run_on(tmp_path, {self.ANY: """\
            class SchedulerBridge:
                def __init__(self):
                    self.round_num = 0

                def bump(self):
                    with self._lock:
                        self.round_num += 1

                def poll(self):  # pta: background-thread
                    with self._lock:
                        self.round_num += 1
        """})
        assert vs == []

    def test_declared_handoff_clean(self, tmp_path):
        contracts = Contracts(
            thread_classes={
                "SchedulerBridge": ThreadContract(
                    handoffs={"round_num": "test: monotonic counter"}
                ),
            },
        )
        vs = run_on(tmp_path, {self.ANY: self.BAD}, contracts)
        assert vs == []

    def test_single_thread_class_clean(self, tmp_path):
        vs = run_on(tmp_path, {self.ANY: """\
            class SchedulerBridge:
                def __init__(self):
                    self.round_num = 0

                def bump(self):
                    self.round_num += 1   # main thread only: fine
        """})
        assert vs == []


class TestPTA005Surface:
    def test_undeclared_and_dynamic_event_flagged(self, tmp_path):
        vs = run_on(tmp_path, {
            "poseidon_tpu/trace.py": """\
                EVENT_TYPES = frozenset({"ROUND", "SCHEDULE"})
            """,
            "poseidon_tpu/other.py": """\
                class T:
                    def go(self, name):
                        self.trace.emit("ROUND")
                        self.trace.emit("BOGUS")
                        self.trace.emit(name)
            """,
        })
        assert codes(vs) == ["PTA005", "PTA005"]
        msgs = " | ".join(v.message for v in vs)
        assert "BOGUS" in msgs and "dynamic" in msgs

    def test_missing_vocab_flagged(self, tmp_path):
        vs = run_on(tmp_path, {"poseidon_tpu/trace.py": """\
            def emit(x):
                pass
        """})
        assert codes(vs) == ["PTA005"]

    def test_undocumented_flag_flagged(self, tmp_path):
        files = {
            "poseidon_tpu/cli.py": """\
                import argparse

                def build_parser():
                    p = argparse.ArgumentParser()
                    p.add_argument("--alpha", type=int)
                    p.add_argument("--hidden", help=argparse.SUPPRESS)
                    return p
            """,
            "README.md": "docs mention --alpha here\n",
            "deploy/poseidon-tpu.cfg": "# no flags here\n",
        }
        vs = run_on(tmp_path, files)
        assert codes(vs) == ["PTA005"]
        assert "--alpha" in vs[0].message
        assert "deploy/poseidon-tpu.cfg" in vs[0].message
        # hidden (SUPPRESS) flags are exempt; documenting --alpha fixes it
        files["deploy/poseidon-tpu.cfg"] = "--alpha=3\n"
        assert run_on(tmp_path, files) == []

    def test_flag_name_prefix_does_not_count(self, tmp_path):
        # "--watch_max_lag" in a doc must NOT satisfy "--watch"
        vs = run_on(tmp_path, {
            "poseidon_tpu/cli.py": """\
                import argparse

                def build_parser():
                    p = argparse.ArgumentParser()
                    p.add_argument("--watch")
                    return p
            """,
            "README.md": "only --watch_max_lag is named\n",
            "deploy/poseidon-tpu.cfg": "--watch=false\n",
        })
        assert codes(vs) == ["PTA005"]
        assert "README.md" in vs[0].message


class TestSuppressions:
    HOT = "poseidon_tpu/ops/resident.py"

    def test_suppression_without_reason_fails(self, tmp_path):
        vs = run_on(tmp_path, {self.HOT: """\
            def f(x):
                return x.item()  # noqa: PTA001
        """})
        # the bare suppression is PTA000 AND suppresses nothing
        assert codes(vs) == ["PTA000", "PTA001"]

    def test_suppression_with_reason_suppresses(self, tmp_path):
        vs = run_on(tmp_path, {self.HOT: """\
            def f(x):
                return x.item()  # noqa: PTA001 -- test fixture: sanctioned
        """})
        assert vs == []

    def test_suppression_only_covers_named_code(self, tmp_path):
        vs = run_on(tmp_path, {self.HOT: """\
            def f(x):
                return x.item()  # noqa: PTA002 -- wrong code named
        """})
        assert codes(vs) == ["PTA001"]


class TestSelfCheck:
    def test_shipped_tree_is_violation_free(self):
        violations, files_scanned = analyze_tree(REPO)
        assert files_scanned > 30
        assert violations == [], "\n".join(
            f"{v.path}:{v.line} {v.code} {v.message}" for v in violations
        )

    def test_injected_item_in_resident_fused_round_fails(self, tmp_path):
        """Acceptance: a stray .item() in the resident round fails CI."""
        src = (REPO / "poseidon_tpu/ops/resident.py").read_text()
        anchor = "        self._warm = state"
        assert anchor in src
        bad = src.replace(
            anchor, "        leak = primal.item()\n" + anchor, 1
        )
        vs = run_on(tmp_path, {"poseidon_tpu/ops/resident.py": bad})
        assert any(
            v.code == "PTA001" and ".item()" in v.message for v in vs
        )

    def test_injected_unlocked_mutation_in_bridge_fails(self, tmp_path):
        """Acceptance: an unlocked cross-thread mutation in the bridge
        fails CI."""
        src = (REPO / "poseidon_tpu/bridge/bridge.py").read_text()
        anchor = "    def cancel_round("
        assert anchor in src
        bad = src.replace(anchor, (
            "    def _bg_refresh(self):  # pta: background-thread\n"
            "        self.round_num += 1\n\n"
        ) + anchor, 1)
        vs = run_on(tmp_path, {"poseidon_tpu/bridge/bridge.py": bad})
        assert any(
            v.code == "PTA004" and "round_num" in v.message for v in vs
        )

    def test_unmodified_copies_stay_clean(self, tmp_path):
        """The injection tests prove the analyzer reacts to the SEED,
        not to analyzing a file in isolation."""
        vs = run_on(tmp_path, {
            "poseidon_tpu/ops/resident.py":
                (REPO / "poseidon_tpu/ops/resident.py").read_text(),
            "poseidon_tpu/bridge/bridge.py":
                (REPO / "poseidon_tpu/bridge/bridge.py").read_text(),
        })
        assert vs == []


class TestCli:
    def test_json_output_clean_exit_zero(self):
        proc = subprocess.run(
            [sys.executable, "-m", "poseidon_tpu.analysis",
             "--format=json"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["count"] == 0
        assert doc["violations"] == []
        assert doc["files_scanned"] > 30

    def test_analyze_file_api_in_fresh_interpreter(self, tmp_path):
        """Regression: the public analyze_file must load the rule
        registry itself — a fresh interpreter using only analyze_file
        must not report a violating file as clean."""
        bad = tmp_path / "poseidon_tpu" / "ops" / "resident.py"
        bad.parent.mkdir(parents=True)
        bad.write_text("def f(x):\n    return x.item()\n")
        proc = subprocess.run(
            [sys.executable, "-c", (
                "import pathlib, sys\n"
                "from poseidon_tpu.analysis import analyze_file\n"
                f"vs = analyze_file(pathlib.Path({str(bad)!r}), "
                f"pathlib.Path({str(tmp_path)!r}))\n"
                "assert [v.code for v in vs] == ['PTA001'], vs\n"
                "print('ok')\n"
            )],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_path_outside_root_exits_two(self, tmp_path):
        stray = tmp_path / "stray.py"
        stray.write_text("x = 1\n")
        proc = subprocess.run(
            [sys.executable, "-m", "poseidon_tpu.analysis",
             "--root", str(REPO / "poseidon_tpu"), str(stray)],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "outside --root" in proc.stderr

    def test_violations_exit_one(self, tmp_path):
        bad = tmp_path / "poseidon_tpu" / "ops" / "resident.py"
        bad.parent.mkdir(parents=True)
        bad.write_text("def f(x):\n    return x.item()\n")
        proc = subprocess.run(
            [sys.executable, "-m", "poseidon_tpu.analysis",
             "--format=json", "--root", str(tmp_path), str(bad)],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["count"] == 1
        assert doc["violations"][0]["code"] == "PTA001"
