"""L1b differential tests: JAX cost-scaling solver vs the C++ oracle."""

from poseidon_tpu.compat import enable_x64
import numpy as np

from poseidon_tpu.graph.network import FlowNetwork
from poseidon_tpu.ops.cost_scaling import solve_cost_scaling, solution_cost
from poseidon_tpu.oracle import solve_oracle

from tests.test_oracle import check_flow, random_instance


def real_flows(net, result):
    return np.asarray(result.flows)[: int(net.n_arcs)].astype(np.int64)


class TestCostScalingBasics:
    def test_single_arc(self):
        net = FlowNetwork.from_arrays([0], [1], [5], [3], [5, -5])
        res = solve_cost_scaling(net)
        assert bool(res.converged)
        assert bool(res.feasible)
        assert real_flows(net, res).tolist() == [5]
        assert solution_cost(net, res) == 15

    def test_cheap_path_preferred(self):
        net = FlowNetwork.from_arrays(
            [0, 0], [1, 1], [1, 5], [1, 10], [3, -3]
        )
        res = solve_cost_scaling(net)
        assert bool(res.feasible)
        assert solution_cost(net, res) == 21

    def test_infeasible_reported(self):
        net = FlowNetwork.from_arrays([0], [1], [2], [1], [5, -5])
        res = solve_cost_scaling(net)
        assert bool(res.converged)
        assert not bool(res.feasible)
        assert int(res.routed) == 2

    def test_zero_supply(self):
        net = FlowNetwork.from_arrays([0], [1], [5], [3], [0, 0])
        res = solve_cost_scaling(net)
        assert bool(res.feasible)
        assert solution_cost(net, res) == 0

    def test_negative_cost(self):
        net = FlowNetwork.from_arrays(
            [0, 0], [1, 1], [2, 2], [-4, 7], [3, -3]
        )
        res = solve_cost_scaling(net)
        assert bool(res.feasible)
        assert solution_cost(net, res) == 2 * -4 + 1 * 7


class TestCostScalingDifferential:
    def test_random_vs_oracle(self):
        rng = np.random.default_rng(777)
        for trial in range(20):
            net = random_instance(rng)
            oracle = solve_oracle(net, "cost_scaling")
            res = solve_cost_scaling(net)
            assert bool(res.converged), f"trial {trial}"
            assert bool(res.feasible), f"trial {trial}"
            assert solution_cost(net, res) == oracle.cost, f"trial {trial}"
            check_flow(net, real_flows(net, res))

    def test_larger_vs_oracle(self):
        rng = np.random.default_rng(31)
        net = random_instance(rng, n_nodes=50, n_arcs=300, max_supply=15)
        oracle = solve_oracle(net, "cost_scaling")
        res = solve_cost_scaling(net)
        assert bool(res.converged)
        assert bool(res.feasible)
        assert solution_cost(net, res) == oracle.cost
        check_flow(net, real_flows(net, res))

    def test_builder_graph_vs_oracle(self):
        from poseidon_tpu.cluster import Machine, Task, make_cluster
        from poseidon_tpu.graph.builder import ArcKind, FlowGraphBuilder

        rng = np.random.default_rng(8)
        cluster = make_cluster(
            [Machine(name=f"m{i}", rack=f"r{i % 3}", max_tasks=4)
             for i in range(6)],
            [Task(uid=f"p{i}", job=f"j{i % 3}",
                  data_prefs={f"m{rng.integers(6)}": 10})
             for i in range(20)],
        )
        net, meta = FlowGraphBuilder().build(cluster)
        h = net.to_host()
        cost = rng.integers(0, 100, size=meta.n_arcs)
        cost[meta.arc_kind == ArcKind.TASK_TO_UNSCHED] = 1000
        net = FlowNetwork.from_arrays(
            h["src"], h["dst"], h["cap"], cost, h["supply"]
        )
        oracle = solve_oracle(net, "ssp")
        res = solve_cost_scaling(net)
        assert bool(res.feasible)
        assert solution_cost(net, res) == oracle.cost
        check_flow(net, real_flows(net, res))


class TestWhatIfBatching:
    def test_vmap_over_costs(self):
        """The BASELINE 'what-if' config: vmap over perturbed cost models
        of one topology, all solved in a single device program."""
        import jax
        import jax.numpy as jnp
        from poseidon_tpu.ops.cost_scaling import _solve

        rng = np.random.default_rng(55)
        base = random_instance(rng)
        K = 8
        costs = np.stack([
            np.asarray(base.cost) + rng.integers(0, 5, size=base.num_arc_slots)
            for _ in range(K)
        ]).astype(np.int32)
        # zero the padding cost slots to stay consistent
        costs[:, int(base.n_arcs):] = 0

        with enable_x64(True):
            batched = jax.vmap(
                lambda c: _solve(base.with_costs(c), 20000, 8)
            )(jnp.asarray(costs))
        for k in range(K):
            net_k = base.with_costs(jnp.asarray(costs[k]))
            oracle = solve_oracle(net_k, "cost_scaling")
            fk = np.asarray(batched.flows[k])[: int(base.n_arcs)]
            assert bool(batched.converged[k])
            assert (fk.astype(np.int64) * np.asarray(net_k.cost)[: int(base.n_arcs)]).sum() == oracle.cost
            check_flow(net_k, fk.astype(np.int64))
