"""Shared fixtures: random clusters + pricing, used across test modules."""

from __future__ import annotations

import numpy as np

from poseidon_tpu.cluster import ClusterState
from poseidon_tpu.graph.builder import FlowGraphBuilder, GraphMeta
from poseidon_tpu.graph.network import FlowNetwork
from poseidon_tpu.models import build_cost_inputs, get_cost_model
from poseidon_tpu.synth import make_synthetic_cluster


def random_cluster(
    rng: np.random.Generator, n_machines: int, n_tasks: int
) -> ClusterState:
    """A randomized small cluster with racks, prefs, jobs, running tasks."""
    return make_synthetic_cluster(
        n_machines,
        n_tasks,
        seed=int(rng.integers(0, 2**31)),
        machines_per_rack=int(rng.integers(2, max(3, n_machines))),
        max_tasks_per_machine=int(rng.integers(1, 6)),
        prefs_per_task=int(rng.integers(0, 4)),
        tasks_per_job=int(rng.integers(1, 6)),
        running_fraction=float(rng.choice([0.0, 0.2])),
    )


def price(
    net: FlowNetwork,
    meta: GraphMeta,
    model: str,
    cluster: ClusterState | None = None,
    **cost_input_kwargs,
) -> FlowNetwork:
    """Price a built network with a named cost model."""
    if cluster is not None:
        pending = cluster.pending()
        cost_input_kwargs.setdefault(
            "task_cpu_milli",
            np.array([int(t.cpu_request * 1000) for t in pending]),
        )
        cost_input_kwargs.setdefault(
            "task_mem_kb", np.array([t.memory_request_kb for t in pending])
        )
    inputs = build_cost_inputs(net, meta, **cost_input_kwargs)
    return net.with_costs(get_cost_model(model)(inputs))


def build_priced(
    rng: np.random.Generator,
    n_machines: int,
    n_tasks: int,
    model: str = "quincy",
):
    """random cluster -> (priced net, meta, cluster)."""
    cluster = random_cluster(rng, n_machines, n_tasks)
    net, meta = FlowGraphBuilder().build(cluster)
    return price(net, meta, model, cluster), meta, cluster
