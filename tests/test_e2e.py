"""The SURVEY §7 minimum end-to-end slice, hermetic.

Fake apiserver (10 nodes / 100 pending pods) -> API client -> bridge ->
priced flow graph -> TPU-path solve -> bindings POSTed back -> every pod
bound and the round cost equals the C++ oracle on the same priced graph.
Exercises every layer; runs on the CPU test platform.
"""

import numpy as np

from poseidon_tpu.apiclient import FakeApiServer, K8sApiClient
from poseidon_tpu.apiclient.client import parse_cpu, parse_memory_kb
from poseidon_tpu.bridge import SchedulerBridge
from poseidon_tpu.cli import parse_args, run_loop
from poseidon_tpu.graph.builder import FlowGraphBuilder
from poseidon_tpu.models import build_cost_inputs, get_cost_model
from poseidon_tpu.oracle import solve_oracle


def _populate(server, n_nodes=10, n_pods=100):
    for i in range(n_nodes):
        server.add_node(
            f"n{i:02d}", cpu="8", memory="16Gi", pods=12,
            rack=f"rack{i % 3}",
        )
    for j in range(n_pods):
        prefs = {f"n{j % n_nodes:02d}": 50} if j % 3 == 0 else None
        server.add_pod(
            f"pod-{j:03d}", cpu="250m", memory="256Mi",
            job=f"job{j // 8}", data_prefs=prefs,
        )


class TestUnitParsing:
    def test_cpu(self):
        assert parse_cpu("100m") == 0.1
        assert parse_cpu("2") == 2.0
        assert parse_cpu(1.5) == 1.5

    def test_memory(self):
        assert parse_memory_kb("128Mi") == 131072
        assert parse_memory_kb("1Gi") == 1 << 20
        assert parse_memory_kb("512Ki") == 512
        assert parse_memory_kb(2048) == 2  # bare bytes
        assert parse_memory_kb("1G") == 976563


class TestEndToEndSlice:
    def test_full_slice_cost_matches_oracle(self):
        with FakeApiServer() as server:
            _populate(server)
            client = K8sApiClient("127.0.0.1", server.port)
            nodes = client.all_nodes()
            pods = client.all_pods()
            assert len(nodes) == 10 and len(pods) == 100
            assert nodes[0].rack.startswith("rack")
            assert pods[0].cpu_request == 0.25

            # small_to_oracle off: this slice specifically exercises
            # the TPU dense path end to end (the production dispatcher
            # would route a 10-node/100-pod cluster to the oracle)
            bridge = SchedulerBridge(
                cost_model="quincy", small_to_oracle=False
            )
            bridge.observe_nodes(nodes)
            bridge.observe_pods(pods)

            # oracle cross-check on the exact same priced graph
            cluster = bridge.cluster_state()
            net, meta = FlowGraphBuilder().build(cluster)
            pending = cluster.pending()
            inputs = build_cost_inputs(
                net, meta,
                task_cpu_milli=np.array(
                    [int(t.cpu_request * 1000) for t in pending]
                ),
                task_mem_kb=np.array(
                    [t.memory_request_kb for t in pending]
                ),
                task_usage=bridge.knowledge.task_cpu_usage(
                    [t.uid for t in pending]
                ),
                machine_load=bridge.knowledge.machine_load(
                    [m.name for m in cluster.machines]
                ),
                machine_mem_free=bridge.knowledge.machine_mem_free(
                    [m.name for m in cluster.machines]
                ),
            )
            priced = net.with_costs(
                get_cost_model("quincy")(inputs)
            )
            o = solve_oracle(priced, algorithm="cost_scaling")

            result = bridge.run_scheduler()
            assert result.stats.cost == o.cost
            assert result.stats.pods_placed == 100

            # POST the bindings; server applies them on the next poll
            for uid, machine in result.bindings.items():
                assert client.bind_pod_to_node(uid, machine)
            assert len(server.bindings) == 100
            pods2 = client.all_pods()
            bound = {p.uid: p.machine for p in pods2}
            for uid, machine in result.bindings.items():
                assert bound[uid] == machine

    def test_driver_loop_binds_everything(self):
        with FakeApiServer() as server:
            _populate(server, n_nodes=6, n_pods=40)
            rc = run_loop(parse_args([
                f"--k8s_apiserver_port={server.port}",
                "--k8s_apiserver_host=127.0.0.1",
                "--flow_scheduling_cost_model=quincy",
                "--polling_frequency=1000",
                "--max_rounds=3",
                "--logtostderr",
            ]))
            assert rc == 0
            assert len(server.bindings) == 40

    def test_poll_failure_skips_tick(self):
        with FakeApiServer() as server:
            _populate(server, n_nodes=2, n_pods=4)
            server.fail_next(10)  # first ticks fail, loop must survive
            rc = run_loop(parse_args([
                f"--k8s_apiserver_port={server.port}",
                "--k8s_apiserver_host=127.0.0.1",
                "--flow_scheduling_cost_model=trivial",
                "--polling_frequency=1000",
                "--max_rounds=2",
            ]))
            assert rc == 0
            assert len(server.bindings) == 4

    def test_integer_cost_model_selector(self):
        # the reference selects cost models by integer
        # (--flow_scheduling_cost_model=6, poseidon.cfg:7)
        with FakeApiServer() as server:
            _populate(server, n_nodes=2, n_pods=4)
            rc = run_loop(parse_args([
                f"--k8s_apiserver_port={server.port}",
                "--k8s_apiserver_host=127.0.0.1",
                "--flow_scheduling_cost_model=6",
                "--polling_frequency=1000",
                "--max_rounds=1",
            ]))
            assert rc == 0
