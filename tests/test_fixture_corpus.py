"""Golden fixture corpus driver (tests/fixtures/analysis/).

One parametrized test per rule code: the known-bad fixture must fire
the rule, the known-good must not. The parametrization enumerates
EVERY rule code, so adding PTA011 without adding its fixtures fails
here — the corpus is how a new rule proves both halves of its
contract (it catches the bug, and the idiomatic fix is clean).

Fixture sources are stored as ``*.py.txt`` (stripped to ``.py`` when
copied into the temp tree) so the deliberately-bad code never enters
the analyzer's own shipped-tree clean run; see the corpus README.
"""

from __future__ import annotations

import importlib.machinery
import importlib.util
import pathlib
import shutil

import pytest

from poseidon_tpu.analysis import analyze_tree

REPO = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "fixtures" / "analysis"

ALL_RULES = tuple(f"PTA{n:03d}" for n in range(11))
JAXPR_RULES = ("PTA008", "PTA009")


def _materialize(side: pathlib.Path, dst: pathlib.Path) -> list:
    """Copy a fixture mini-tree, stripping the .txt armor."""
    paths = []
    for src in sorted(side.rglob("*")):
        if src.is_dir():
            continue
        rel = src.relative_to(side).as_posix()
        if rel.endswith(".py.txt"):
            rel = rel[: -len(".txt")]
        out = dst / rel
        out.parent.mkdir(parents=True, exist_ok=True)
        shutil.copyfile(src, out)
        if rel.endswith(".py"):
            paths.append(out)
    return paths


def _load_fixture_module(code: str):
    path = FIXTURES / code / "fixture.py.txt"
    loader = importlib.machinery.SourceFileLoader(
        f"_corpus_{code.lower()}", str(path)
    )
    spec = importlib.util.spec_from_loader(loader.name, loader)
    mod = importlib.util.module_from_spec(spec)
    loader.exec_module(mod)
    return mod


def _jaxpr_fires(code: str, mod, which: str) -> bool:
    import jax

    fn = getattr(mod, which)
    args = mod.example_args()[which]
    closed = jax.make_jaxpr(fn)(*args)
    if code == "PTA008":
        from poseidon_tpu.analysis.jaxpr_check import structural_problems

        return bool(structural_problems("fixture", closed))
    from poseidon_tpu.analysis.padding_taint import analyze_kernel

    return bool(analyze_kernel("fixture", closed))


@pytest.mark.parametrize("code", ALL_RULES)
def test_fixture_pair(code, tmp_path):
    root = FIXTURES / code
    assert root.is_dir(), (
        f"no fixture corpus for {code}: adding a rule requires adding "
        f"its bad/good pair under {root}"
    )
    if code in JAXPR_RULES:
        mod = _load_fixture_module(code)
        assert _jaxpr_fires(code, mod, "bad"), (
            f"{code} bad fixture did not fire"
        )
        assert not _jaxpr_fires(code, mod, "good"), (
            f"{code} good fixture fired"
        )
        return
    for side, expect in (("bad", True), ("good", False)):
        dst = tmp_path / side
        paths = _materialize(root / side, dst)
        assert paths, f"{code}/{side} has no Python fixtures"
        violations, _ = analyze_tree(dst, paths)
        fired = any(v.code == code for v in violations)
        assert fired == expect, (
            f"{code}/{side}: expected fired={expect}, got "
            + "; ".join(f"{v.code} {v.path}:{v.line}" for v in violations)
        )
