"""The multi-tenant service lane (poseidon_tpu/service/).

The load-bearing claims, each pinned here:

- **Per-tenant exactness**: a tenant solved inside a padded shape
  bucket (other tenants' instances stacked alongside) gets exactly the
  bindings it would get solo — bit-identical assignments across >= 3
  cost models, with preemption on and off, and across fuzzed shape
  mixes within one bucket.
- **Zero steady-state recompiles**: after warmup, waves of churning
  tenant shapes dispatch with ZERO XLA compiles (grow-only bucket
  floors, the CompileCounter budget from PR 8 applied to the service
  loop).
- **Isolation**: tenants share the device but nothing else — no
  tenant's uid ever appears in another tenant's trace or decision log.
- **Budget actionability**: a batched shape that blows the HBM budget
  names the largest n_variants that would fit.
"""

from __future__ import annotations

import numpy as np
import pytest

from poseidon_tpu.bridge import SchedulerBridge
from poseidon_tpu.cluster import Task, TaskPhase
from poseidon_tpu.guards import CompileCounter
from poseidon_tpu.ops import dense_auction
from poseidon_tpu.ops.batch import solve_heterogeneous
from poseidon_tpu.ops.dense_auction import (
    DenseMemoryTooLarge,
    check_table_budget,
    max_variants_for,
    solve_transport_dense,
)
from poseidon_tpu.ops.transport import extract_instance
from poseidon_tpu.service import SchedulingService
from poseidon_tpu.synth import make_synthetic_cluster
from tests.helpers import build_priced

MODELS = ("quincy", "coco", "octopus")


def _tenant_cluster(i: int, *, n_machines=None, n_tasks=None,
                    running_fraction=0.0, seed=None, prefix=""):
    """A small tenant cluster; defaults keep every tenant in the same
    (32, 16) padding bucket while T/M stay heterogeneous. ``prefix``
    namespaces uids/machines per tenant (the synth generator reuses
    names, which would make cross-tenant isolation asserts vacuous)."""
    import dataclasses as _dc

    cluster = make_synthetic_cluster(
        n_machines if n_machines is not None else 5 + (i % 4),
        n_tasks if n_tasks is not None else 18 + 4 * (i % 4),
        seed=seed if seed is not None else 1000 + i,
        prefs_per_task=2,
        running_fraction=running_fraction,
    )
    if not prefix:
        return cluster
    machines = [
        _dc.replace(m, name=f"{prefix}{m.name}")
        for m in cluster.machines
    ]
    tasks = [
        _dc.replace(
            t,
            uid=f"{prefix}{t.uid}",
            machine=f"{prefix}{t.machine}" if t.machine else "",
            data_prefs={
                (f"{prefix}{k}" if k.startswith("m") else k): v
                for k, v in t.data_prefs.items()
            },
        )
        for t in cluster.tasks
    ]
    return _dc.replace(cluster, machines=machines, tasks=tasks)


def _feed(service, tid, cluster):
    bridge = service.sessions[tid].bridge
    bridge.observe_nodes(cluster.machines)
    bridge.observe_pods(cluster.tasks)


def _round_all(service, tenants):
    """Submit one round for every tenant, run the pipeline to
    completion, return {tenant: RoundResult}."""
    futs = {t: service.submit(t) for t in tenants}
    service.pump()
    service.flush()
    return {t: f.result(timeout=60) for t, f in futs.items()}


class TestHeterogeneousKernel:
    """ops/batch.solve_heterogeneous: the bucket kernel itself."""

    def test_bit_identity_mixed_shapes_and_models(self):
        rng = np.random.default_rng(7)
        insts, solo = [], []
        for shape, model in [((5, 18), "quincy"), ((7, 26), "coco"),
                             ((8, 31), "octopus")]:
            net, meta, _ = build_priced(rng, *shape, model=model)
            inst = extract_instance(net, meta)
            insts.append(inst)
            solo.append(solve_transport_dense(inst)[0])
        br = solve_heterogeneous(insts)
        for b, (inst, res) in enumerate(zip(insts, solo)):
            T = inst.n_tasks
            assert bool(br.converged[b])
            assert int(br.costs[b]) == res.cost
            assert np.array_equal(br.assignments[b, :T], res.assignment)

    def test_fuzz_shape_mix_within_bucket(self):
        """Random tenant shapes (different natural pads mixed into one
        max bucket) stay bit-identical to their solo solves."""
        rng = np.random.default_rng(21)
        for trial in range(3):
            insts, solo = [], []
            for k in range(4):
                m = int(rng.integers(3, 12))
                t = int(rng.integers(8, 40))
                model = MODELS[int(rng.integers(0, len(MODELS)))]
                net, meta, _ = build_priced(rng, m, t, model=model)
                inst = extract_instance(net, meta)
                insts.append(inst)
                solo.append(solve_transport_dense(inst)[0])
            br = solve_heterogeneous(insts)
            for b, (inst, res) in enumerate(zip(insts, solo)):
                T = inst.n_tasks
                assert bool(br.converged[b]), (trial, b)
                assert int(br.costs[b]) == res.cost, (trial, b)
                assert np.array_equal(
                    br.assignments[b, :T], res.assignment
                ), (trial, b)

    def test_empty_batch(self):
        br = solve_heterogeneous([])
        assert br.costs.shape == (0,)


class TestServiceExactness:
    """Service-level: a bucketed tenant round == its solo solve."""

    @pytest.mark.parametrize("model", MODELS)
    def test_cold_round_bit_identical_to_solo(self, model):
        service = SchedulingService()
        tenants = []
        for i in range(3):
            tid = f"t{i}"
            # the tenant under test runs `model`; its bucket-mates run
            # a DIFFERENT model each, so the batch is heterogeneous in
            # cost model as well as shape
            m = model if i == 0 else MODELS[(MODELS.index(model) + i)
                                            % len(MODELS)]
            service.add_tenant(tid, cost_model=m)
            _feed(service, tid, _tenant_cluster(i))
            tenants.append(tid)
        results = _round_all(service, tenants)
        for tid in tenants:
            r = results[tid]
            assert r.stats.backend == "dense_service"
            solver = service.sessions[tid].solver
            res, _ = solve_transport_dense(solver.last_instance)
            assert res.converged
            assert r.stats.cost == res.cost
            assert np.array_equal(solver.last_assignment,
                                  res.assignment)

    @pytest.mark.parametrize("preemption", [False, True])
    def test_bridge_differential_vs_solo_scheduler(self, preemption):
        """A service tenant's whole ROUND (bindings + migrations +
        preemptions + cost) equals a standalone scheduler's round over
        the same observations — the bucket, the other tenants, and the
        shared dispatcher change nothing."""
        cluster = _tenant_cluster(
            0, n_machines=6, n_tasks=24,
            running_fraction=0.25 if preemption else 0.0, seed=77,
        )
        # solo: its own bridge + ResidentSolver (dense lane forced)
        solo = SchedulerBridge(
            cost_model="quincy", small_to_oracle=False,
            enable_preemption=preemption,
        )
        solo.observe_nodes(cluster.machines)
        solo.observe_pods(cluster.tasks)
        solo_result = solo.run_scheduler()

        service = SchedulingService()
        service.add_tenant(
            "t0", cost_model="quincy", enable_preemption=preemption
        )
        # a bucket-mate with a different shape and model
        service.add_tenant("t1", cost_model="coco")
        _feed(service, "t0", cluster)
        _feed(service, "t1", _tenant_cluster(1, seed=78))
        results = _round_all(service, ["t0", "t1"])
        svc_result = results["t0"]
        assert svc_result.bindings == solo_result.bindings
        assert svc_result.migrations == solo_result.migrations
        assert svc_result.preemptions == solo_result.preemptions
        assert svc_result.stats.cost == solo_result.stats.cost

    def test_warm_round_stays_optimal(self):
        """Second (warm-context) waves certify and land on the same
        optimum a cold solo solve finds."""
        service = SchedulingService()
        for i in range(2):
            service.add_tenant(f"t{i}", cost_model="quincy")
            _feed(service, f"t{i}", _tenant_cluster(i))
        _round_all(service, ["t0", "t1"])
        results = _round_all(service, ["t0", "t1"])  # warm wave
        for tid in ("t0", "t1"):
            r = results[tid]
            assert r.stats.backend == "dense_service"
            solver = service.sessions[tid].solver
            res, _ = solve_transport_dense(solver.last_instance)
            assert r.stats.cost == res.cost
            assert np.array_equal(solver.last_assignment,
                                  res.assignment)

    def test_chunked_dispatch_still_exact(self):
        """max_batch smaller than the wave splits a bucket into several
        chunks (each one upload + one batched fetch) without changing
        any tenant's answer."""
        service = SchedulingService(max_batch=2)
        tenants = []
        for i in range(5):
            tid = f"t{i}"
            service.add_tenant(tid, cost_model="quincy")
            _feed(service, tid, _tenant_cluster(i, seed=300 + i))
            tenants.append(tid)
        results = _round_all(service, tenants)
        assert service.dispatcher.dispatches >= 2
        for tid in tenants:
            solver = service.sessions[tid].solver
            res, _ = solve_transport_dense(solver.last_instance)
            assert results[tid].stats.cost == res.cost
            assert np.array_equal(solver.last_assignment,
                                  res.assignment)


def _churn(cluster, rng, round_no):
    """Mutate a tenant's pod list in place: retire a couple of pending
    pods, add a couple of new ones (<= 2 prefs each, so the pref-width
    floor holds). Task counts oscillate but stay inside the warmed
    padding bucket."""
    tasks = [t for t in cluster.tasks if t.phase == TaskPhase.PENDING]
    keep = tasks[2:] if len(tasks) > 10 else tasks
    machines = cluster.machines
    new = [
        Task(
            uid=f"{machines[0].name}-new-{round_no}-{k}",
            job=f"job-new-{round_no}",
            cpu_request=0.25,
            memory_request_kb=1 << 18,
            data_prefs={
                machines[int(rng.integers(0, len(machines)))].name:
                    int(rng.integers(20, 120))
            },
        )
        for k in range(2)
    ]
    cluster.tasks[:] = keep + new
    return cluster


class TestZeroRecompile:
    def test_steady_state_waves_compile_nothing(self):
        """After a 2-wave warmup (cold + warm variants compile there),
        >= 3 further waves of churning tenant shapes run with ZERO XLA
        compiles: bucket dims, batch width, smax, and pricing pads all
        ride grow-only floors."""
        rng = np.random.default_rng(5)
        service = SchedulingService()
        clusters = {}
        for i in range(3):
            tid = f"t{i}"
            service.add_tenant(tid, cost_model="quincy")
            clusters[tid] = _tenant_cluster(i, seed=500 + i)
            _feed(service, tid, clusters[tid])
        tenants = list(clusters)
        _round_all(service, tenants)   # wave 1: cold compiles
        _round_all(service, tenants)   # wave 2: warm variant compiles
        counter = CompileCounter()
        with counter:
            for w in range(3):
                for tid in tenants:
                    c = _churn(clusters[tid], rng, w)
                    bridge = service.sessions[tid].bridge
                    bridge.observe_nodes(c.machines)
                    bridge.observe_pods(c.tasks)
                results = _round_all(service, tenants)
                for tid, r in results.items():
                    assert r.stats.backend == "dense_service", (
                        w, tid, r.stats.backend
                    )
        if not counter.supported:
            pytest.skip("jax.monitoring unavailable")
        assert counter.count == 0, (
            f"{counter.count} steady-state recompiles in the service "
            f"loop under churning tenant shapes"
        )


class TestIsolation:
    def test_no_cross_tenant_uids_in_trace_or_decision_log(self):
        service = SchedulingService()
        clusters = {}
        for i in range(3):
            tid = f"t{i}"
            service.add_tenant(tid, cost_model=MODELS[i])
            clusters[tid] = _tenant_cluster(
                i, seed=900 + i, prefix=f"{tid}-"
            )
            _feed(service, tid, clusters[tid])
        tenants = list(clusters)
        results = _round_all(service, tenants)
        # confirm + re-round so RUNNING state and a second wave's
        # events land in the streams too
        for tid, r in results.items():
            for uid, machine in r.bindings.items():
                service.sessions[tid].bridge.confirm_binding(
                    uid, machine
                )
        _round_all(service, tenants)
        uids = {
            tid: {t.uid for t in clusters[tid].tasks}
            for tid in tenants
        }
        for tid in tenants:
            session = service.sessions[tid]
            own = uids[tid]
            foreign = set().union(
                *(uids[o] for o in tenants if o != tid)
            )
            for ev in session.trace.events:
                if ev.task:
                    assert ev.task in own, (tid, ev.task)
                    assert ev.task not in foreign
            for _round, _kind, uid, _detail in session.bridge.decision_log:
                assert uid in own, (tid, uid)

    def test_per_tenant_stats_isolated(self):
        service = SchedulingService()
        for i in range(2):
            service.add_tenant(f"t{i}", cost_model="quincy")
            _feed(service, f"t{i}", _tenant_cluster(i, seed=910 + i))
        results = _round_all(service, ["t0", "t1"])
        assert results["t0"].stats.round_num == 1
        assert results["t1"].stats.round_num == 1
        assert results["t0"].stats.lane == "service"
        placed = {t: r.stats.pods_placed for t, r in results.items()}
        # distinct clusters, distinct counts — nothing shared
        assert placed["t0"] == len(results["t0"].bindings)
        assert placed["t1"] == len(results["t1"].bindings)


class TestBudgetMessage:
    def test_batched_overflow_suggests_largest_fitting_batch(
        self, monkeypatch
    ):
        monkeypatch.setattr(
            dense_auction, "DENSE_TABLE_BUDGET_BYTES", 64 << 20
        )
        # one 2048x2048 table = 16 MiB -> 4 fit, 8 do not
        with pytest.raises(DenseMemoryTooLarge) as ei:
            check_table_budget(2048, 2048, 8)
        msg = str(ei.value)
        assert "n_variants <= 4" in msg
        assert "--serve_max_batch" in msg
        assert max_variants_for(2048, 2048) == 4

    def test_single_instance_overflow_keeps_mesh_hint(
        self, monkeypatch
    ):
        monkeypatch.setattr(
            dense_auction, "DENSE_TABLE_BUDGET_BYTES", 1 << 20
        )
        with pytest.raises(DenseMemoryTooLarge) as ei:
            check_table_budget(2048, 2048, 1)
        msg = str(ei.value)
        assert "n_variants" not in msg     # unbatched shape: no hint
        assert "--mesh_width" in msg

    def test_dispatcher_chunks_against_budget(self, monkeypatch):
        """A wave wider than the budget's fit splits into fitting
        chunks instead of raising (and every tenant still solves)."""
        # ~1.07 MiB budget: each (32, 16) member costs ~few KiB, but
        # shrink until only 2 fit to force the split deterministically
        from poseidon_tpu.service import dispatch as dispatch_mod

        real_fit = dispatch_mod.max_variants_for

        def tiny_fit(Tp, Mp, side_ints_per_variant=0, **kw):
            return min(real_fit(
                Tp, Mp, side_ints_per_variant=side_ints_per_variant,
                **kw,
            ), 2)

        monkeypatch.setattr(dispatch_mod, "max_variants_for", tiny_fit)
        service = SchedulingService()
        tenants = []
        for i in range(5):
            tid = f"t{i}"
            service.add_tenant(tid, cost_model="quincy")
            _feed(service, tid, _tenant_cluster(i, seed=700 + i))
            tenants.append(tid)
        results = _round_all(service, tenants)
        assert service.dispatcher.dispatches >= 3
        for tid in tenants:
            solver = service.sessions[tid].solver
            res, _ = solve_transport_dense(solver.last_instance)
            assert results[tid].stats.cost == res.cost


class TestFrontDoor:
    def test_tenant_resubmitted_while_in_flight_waits_a_wave(self):
        service = SchedulingService()
        service.add_tenant("t0", cost_model="quincy")
        _feed(service, "t0", _tenant_cluster(0, seed=40))
        f1 = service.submit("t0")
        service.pump()             # wave 1 in flight
        f2 = service.submit("t0")  # must NOT join the in-flight wave
        service.pump()             # finishes wave 1, starts wave 2
        assert f1.done()
        service.flush()
        assert f2.done()
        assert f1.result().stats.round_num == 1
        assert f2.result().stats.round_num == 2

    def test_unknown_tenant_raises(self):
        service = SchedulingService()
        with pytest.raises(KeyError):
            service.submit("nope")

    def test_empty_round_resolves_synchronously(self):
        service = SchedulingService()
        service.add_tenant("t0", cost_model="quincy")
        # machines but no pods: nothing schedulable
        cluster = _tenant_cluster(0, n_tasks=0, seed=41)
        _feed(service, "t0", cluster)
        fut = service.submit("t0")
        service.pump()
        assert fut.done()
        assert fut.result().bindings == {}

    def test_non_taxonomy_or_oracle_degrade_is_loud(self):
        """An uncertifiable tenant degrades alone (backend oracle:*),
        without touching its bucket-mates."""
        service = SchedulingService()
        service.add_tenant("t0", cost_model="quincy")
        _feed(service, "t0", _tenant_cluster(0, seed=42))
        # poison the budget so t0's registration degrades to oracle
        import poseidon_tpu.service.dispatch as dispatch_mod

        def no_fit(*a, **kw):
            raise DenseMemoryTooLarge("forced by test")

        orig = dispatch_mod.check_table_budget
        dispatch_mod.check_table_budget = no_fit
        try:
            results = _round_all(service, ["t0"])
        finally:
            dispatch_mod.check_table_budget = orig
        assert results["t0"].stats.backend == "oracle:memory-envelope"
        # degraded rounds still place exactly (the oracle is exact)
        assert results["t0"].stats.pods_placed > 0


class TestServeDriver:
    def test_serve_e2e_three_fake_tenants(self):
        """The --serve loop end to end: 3 heterogeneous fake-apiserver
        tenants, every pod bound on ITS OWN apiserver, no cross-tenant
        binding leakage."""
        import contextlib

        from poseidon_tpu.cli import main
        from poseidon_tpu.service import serve as serve_mod

        captured = {}
        real = serve_mod._fake_tenants

        def capture(n, stack):
            out = real(n, stack)
            captured["tenants"] = [
                (tid, server) for tid, server, _m, _p in out
            ]
            return out

        with contextlib.ExitStack() as stack:
            serve_mod._fake_tenants = capture
            stack.callback(
                lambda: setattr(serve_mod, "_fake_tenants", real)
            )
            rc = main([
                "--serve=true",
                "--serve_tenants=3",
                "--polling_frequency=100000",
                "--max_rounds=8",
            ])
        assert rc == 0
        assert len(captured["tenants"]) == 3
        for tid, server in captured["tenants"]:
            i = tid.split("-")[1]
            assert len(server.bindings) == len(server.pods), tid
            for key, node in server.bindings:
                # tenant i's pods bind only to tenant i's nodes
                assert key.startswith(f"default/t{i}-pod-"), key
                assert node.startswith(f"t{i}-n"), (key, node)
