"""List pagination + the bridge's mass-eviction guard.

The reference does one unpaginated GET per resource and trusts whatever
came back (k8s_api_client.cc:100-160). Against an apiserver that chunks
its lists (``limit``/``continue``) that drops every item after page one,
and a truncated response reads as mass deletion — one bad poll would
evict most of the scheduler's state. These tests pin both defenses:

- the client follows ``metadata.continue`` tokens until the list is
  complete (round-3 verdict, Next #7);
- the bridge holds a >50% disappearance for ``SHRINK_STRIKES``
  consecutive polls before honoring it, and still honors a persistent
  (real) shrink afterwards.
"""

from __future__ import annotations

from poseidon_tpu.apiclient import FakeApiServer, K8sApiClient
from poseidon_tpu.bridge import SchedulerBridge
from poseidon_tpu.bridge.bridge import SHRINK_STRIKES
from poseidon_tpu.cluster import TaskPhase


def _fill(server: FakeApiServer, nodes: int, pods: int) -> None:
    for i in range(nodes):
        server.add_node(f"node-{i:03d}", rack=f"rack-{i % 3}")
    for i in range(pods):
        server.add_pod(f"pod-{i:03d}")


class TestClientPagination:
    def test_follows_continue_tokens(self):
        with FakeApiServer() as server:
            _fill(server, nodes=23, pods=57)
            client = K8sApiClient(port=server.port, page_limit=10)
            nodes = client.all_nodes()
            pods = client.all_pods()
        assert sorted(n.name for n in nodes) == sorted(
            f"node-{i:03d}" for i in range(23)
        )
        assert sorted(p.uid for p in pods) == sorted(
            f"default/pod-{i:03d}" for i in range(57)
        )

    def test_single_page_when_under_limit(self):
        with FakeApiServer() as server:
            _fill(server, nodes=3, pods=4)
            client = K8sApiClient(port=server.port, page_limit=500)
            before = server.requests_served
            assert len(client.all_nodes()) == 3
            assert server.requests_served == before + 1

    def test_selector_applies_across_pages(self):
        with FakeApiServer() as server:
            _fill(server, nodes=12, pods=0)
            client = K8sApiClient(port=server.port, page_limit=5)
            rack0 = client.nodes_with_label("rack=rack-0")
        assert sorted(n.name for n in rack0) == sorted(
            f"node-{i:03d}" for i in range(12) if i % 3 == 0
        )


class TestMassEvictionGuard:
    def _observe(self, bridge, client):
        bridge.observe_nodes(client.all_nodes())
        bridge.observe_pods(client.all_pods())

    def test_truncated_snapshot_does_not_evict(self):
        with FakeApiServer() as server:
            _fill(server, nodes=10, pods=40)
            client = K8sApiClient(port=server.port)
            bridge = SchedulerBridge()
            self._observe(bridge, client)
            assert len(bridge.machines) == 10
            assert len(bridge.tasks) == 40

            # one faulty poll: only 2 nodes / 5 pods come back, with no
            # continue token — a partial snapshot masquerading as full
            server.truncate_lists(2)
            bridge.observe_nodes(client.all_nodes())
            server.truncate_lists(5)
            bridge.observe_pods(client.all_pods())
            assert len(bridge.machines) == 10, "held, not evicted"
            assert len(bridge.tasks) == 40, "held, not retired"

            # recovery: the next full poll clears the strike counters
            server.truncate_lists(0)
            self._observe(bridge, client)
            assert len(bridge.machines) == 10
            assert len(bridge.tasks) == 40
            assert bridge._node_shrink_strikes == 0
            assert bridge._pod_shrink_strikes == 0

    def test_persistent_shrink_is_honored(self):
        with FakeApiServer() as server:
            _fill(server, nodes=10, pods=40)
            client = K8sApiClient(port=server.port)
            bridge = SchedulerBridge()
            self._observe(bridge, client)

            # a real teardown: most pods deleted, most nodes drained
            for i in range(3, 10):
                server.drop_node(f"node-{i:03d}")
            with server._lock:
                for i in range(10, 40):
                    server.pods.pop(f"default/pod-{i:03d}", None)

            for _ in range(SHRINK_STRIKES - 1):
                self._observe(bridge, client)
                assert len(bridge.machines) == 10  # still holding
                assert len(bridge.tasks) == 40
            self._observe(bridge, client)  # strike limit reached
            assert len(bridge.machines) == 3
            assert len(bridge.tasks) == 10

    def test_truncated_snapshot_with_new_names_still_held(self):
        # the guard's denominator is the PRE-upsert known count: a
        # truncated poll that also carries new names must not inflate
        # it past the >50% threshold (mid-rollover partial cache)
        with FakeApiServer() as server:
            _fill(server, nodes=10, pods=0)
            client = K8sApiClient(port=server.port)
            bridge = SchedulerBridge()
            bridge.observe_nodes(client.all_nodes())
            assert len(bridge.machines) == 10
            # 4 survivors + 3 brand-new nodes; 6 of 10 known vanish
            with server._lock:
                survivors = {f"node-{i:03d}" for i in range(4)}
                for name in list(server.nodes):
                    if name not in survivors:
                        del server.nodes[name]
            for i in range(3):
                server.add_node(f"fresh-{i}")
            bridge.observe_nodes(client.all_nodes())
            # held: the 6 missing stay known, the 3 new are upserted
            assert len(bridge.machines) == 13
            assert bridge._node_shrink_strikes == 1

    def test_small_clusters_evict_immediately(self):
        # the guard only arms at SHRINK_MIN_KNOWN entities: a 3-node dev
        # cluster dropping 2 nodes is ordinary, not implausible
        with FakeApiServer() as server:
            _fill(server, nodes=3, pods=4)
            client = K8sApiClient(port=server.port)
            bridge = SchedulerBridge()
            self._observe(bridge, client)
            server.drop_node("node-001")
            server.drop_node("node-002")
            self._observe(bridge, client)
            assert set(bridge.machines) == {"node-000"}

    def test_plausible_shrink_unaffected(self):
        with FakeApiServer() as server:
            _fill(server, nodes=10, pods=40)
            client = K8sApiClient(port=server.port)
            bridge = SchedulerBridge()
            self._observe(bridge, client)
            server.drop_node("node-009")
            with server._lock:
                for i in range(35, 40):
                    server.pods.pop(f"default/pod-{i:03d}", None)
            self._observe(bridge, client)
            assert len(bridge.machines) == 9
            assert len(bridge.tasks) == 35

    def test_held_pods_keep_phase(self):
        # a held pod snapshot must not corrupt task phases: pods absent
        # from the truncated list keep their recorded state
        with FakeApiServer() as server:
            _fill(server, nodes=10, pods=40)
            client = K8sApiClient(port=server.port)
            bridge = SchedulerBridge()
            self._observe(bridge, client)
            server.truncate_lists(5)
            bridge.observe_pods(client.all_pods())
            assert all(
                t.phase == TaskPhase.PENDING
                for t in bridge.tasks.values()
            )
