"""Cost-model layer tests: registry, pricing semantics, vmap batching.

Models are validated end to end: builder -> cost inputs -> priced network
-> exact solve, checked against the C++ oracle (the seam the reference
exercises via --flow_scheduling_cost_model, deploy/poseidon.cfg:7).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from poseidon_tpu.cluster import ClusterState, Machine, Task
from poseidon_tpu.graph.builder import ArcKind, FlowGraphBuilder
from poseidon_tpu.models import (
    COST_CAP,
    COST_MODELS,
    COST_MODEL_SELECTORS,
    KnowledgeBase,
    MachineSample,
    TaskSample,
    build_cost_inputs,
    get_cost_model,
    quincy_cost,
    octopus_cost,
)
from poseidon_tpu.ops.ssp import solve_ssp, solution_cost
from poseidon_tpu.oracle.oracle import solve_oracle


def small_cluster(n_machines=4, n_tasks=12, prefs=True, seed=0):
    rng = np.random.default_rng(seed)
    machines = [
        Machine(name=f"m{i}", rack=f"r{i // 2}", max_tasks=4)
        for i in range(n_machines)
    ]
    tasks = []
    for j in range(n_tasks):
        data = {}
        if prefs:
            data = {f"m{rng.integers(0, n_machines)}": int(rng.integers(10, 90))}
        tasks.append(
            Task(uid=f"p{j}", job=f"j{j % 3}", data_prefs=data,
                 cpu_request=0.25, memory_request_kb=1 << 18)
        )
    return ClusterState(machines=machines, tasks=tasks)


def priced(cluster, model_name, kb=None):
    net, meta = FlowGraphBuilder().build(cluster)
    machines = [m.name for m in cluster.machines]
    kwargs = {}
    if kb is not None:
        kwargs["machine_load"] = kb.machine_load(machines)
        kwargs["machine_mem_free"] = kb.machine_mem_free(machines)
    inputs = build_cost_inputs(
        net, meta,
        task_cpu_milli=np.array(
            [int(t.cpu_request * 1000) for t in cluster.pending()]),
        task_mem_kb=np.array(
            [t.memory_request_kb for t in cluster.pending()]),
        **kwargs,
    )
    cost = get_cost_model(model_name)(inputs)
    return net.with_costs(cost), meta, inputs


class TestRegistry:
    def test_names_and_selectors(self):
        for name in COST_MODELS:
            assert get_cost_model(name) is COST_MODELS[name]
        for sel, name in COST_MODEL_SELECTORS.items():
            assert get_cost_model(sel) is COST_MODELS[name]
        # the reference's shipped config selects 6 = load balancing
        assert COST_MODEL_SELECTORS[6] == "octopus"

    def test_unknown_raises(self):
        with pytest.raises(KeyError):
            get_cost_model("nope")
        with pytest.raises(KeyError):
            get_cost_model(99)


class TestPricingSemantics:
    @pytest.mark.parametrize("name", sorted(COST_MODELS))
    def test_bounds_and_padding(self, name):
        net, meta, inputs = priced(small_cluster(), name)
        c = np.asarray(net.cost)
        assert c.min() >= 0 and c.max() <= COST_CAP
        assert (c[meta.n_arcs:] == 0).all(), "padding arcs must cost 0"

    def test_quincy_prefers_local_machine(self):
        cluster = small_cluster(prefs=True)
        net, meta, inputs = priced(cluster, "quincy")
        c = np.asarray(net.cost)[: meta.n_arcs]
        pref = meta.arc_kind == int(ArcKind.TASK_TO_MACHINE)
        wild = meta.arc_kind == int(ArcKind.TASK_TO_CLUSTER)
        # every pref arc is cheaper than the same task's wildcard arc
        for ti in np.unique(meta.arc_task[pref]):
            p = c[pref & (meta.arc_task == ti)].min()
            w = c[wild & (meta.arc_task == ti)].min()
            assert p < w

    def test_quincy_wait_raises_unsched_cost(self):
        cluster = small_cluster()
        impatient = ClusterState(
            machines=cluster.machines,
            tasks=[Task(uid=t.uid, job=t.job, data_prefs=t.data_prefs,
                        wait_rounds=7) for t in cluster.tasks],
        )
        _, meta0, i0 = priced(cluster, "quincy")
        _, meta7, i7 = priced(impatient, "quincy")
        c0 = np.asarray(quincy_cost(i0))[: meta0.n_arcs]
        c7 = np.asarray(quincy_cost(i7))[: meta7.n_arcs]
        uns = meta0.arc_kind == int(ArcKind.TASK_TO_UNSCHED)
        assert (c7[uns] > c0[uns]).all()

    def test_octopus_prices_busy_machines_up(self):
        cluster = small_cluster(prefs=False)
        kb = KnowledgeBase()
        for i, m in enumerate(cluster.machines):
            # m0 idle ... m3 slammed
            kb.add_machine_sample(
                m.name, MachineSample(cpu_idle=1.0 - i / 3.0,
                                      mem_free_frac=1.0))
        net, meta, inputs = priced(cluster, "octopus", kb=kb)
        c = np.asarray(net.cost)[: meta.n_arcs]
        sink = meta.arc_kind == int(ArcKind.MACHINE_TO_SINK)
        per_machine = {meta.arc_machine[i]: c[i]
                       for i in np.where(sink)[0]}
        assert per_machine[0] < per_machine[3]

    def test_knowledge_base_ring_bound(self):
        kb = KnowledgeBase(queue_size=4)
        for v in [0.0, 0.0, 0.0, 0.0, 1.0, 1.0, 1.0, 1.0]:
            kb.add_machine_sample("m", MachineSample(cpu_idle=v,
                                                     mem_free_frac=v))
        # ring of 4 keeps only the last four samples
        assert kb.machine_cpu_idle(["m"])[0] == pytest.approx(1.0)
        kb.add_task_sample("t", TaskSample(cpu_usage=0.5, mem_usage_kb=10))
        assert kb.task_cpu_usage(["t"])[0] == pytest.approx(0.5)


class TestEndToEnd:
    @pytest.mark.parametrize("name", ["trivial", "quincy", "octopus"])
    def test_model_priced_solve_matches_oracle(self, name):
        cluster = small_cluster(n_machines=4, n_tasks=10)
        kb = KnowledgeBase()
        for i, m in enumerate(cluster.machines):
            kb.add_machine_sample(
                m.name, MachineSample(cpu_idle=0.9 - 0.2 * i,
                                      mem_free_frac=0.8))
        net, meta, _ = priced(cluster, name, kb=kb)
        res = solve_ssp(net)
        assert bool(res.feasible)
        oracle = solve_oracle(net, "cost_scaling")
        assert solution_cost(net, res) == oracle.cost

    def test_vmap_what_if_over_load_perturbations(self):
        """BASELINE config 5 seam: one compiled program prices B scenarios."""
        cluster = small_cluster(prefs=False)
        net, meta, inputs = priced(cluster, "octopus")
        B = 8
        loads = jnp.linspace(0.0, 1.0, B)[:, None] * jnp.ones(
            (B, inputs.machine_load.shape[0]))

        @jax.jit
        def batch_costs(load):  # noqa: PTA003 -- test-local one-shot jit: the closure over `inputs` is the vmap-what-if fixture under test, traced exactly once
            import dataclasses as dc
            return jax.vmap(
                lambda ld: octopus_cost(dc.replace(inputs, machine_load=ld))
            )(load)

        costs = np.asarray(batch_costs(loads))
        assert costs.shape[0] == B
        sink = np.asarray(inputs.kind) == int(ArcKind.MACHINE_TO_SINK)
        # heavier load scenario -> uniformly pricier machine arcs
        assert (costs[-1][sink] >= costs[0][sink]).all()
        assert (costs[-1][sink] > costs[0][sink]).any()


class TestKnowledgeRetirement:
    def test_retired_rows_are_reused(self):
        from poseidon_tpu.models.knowledge import KnowledgeBase, TaskSample

        kb = KnowledgeBase(queue_size=4)
        for i in range(1000):
            uid = f"pod-{i}"
            kb.add_task_sample(uid, TaskSample(cpu_usage=0.5, mem_usage_kb=1))
            kb.retire_task(uid)
        # churned uids reuse one freed row; storage must not have grown
        assert kb._tasks._count.shape[0] == 256
        assert len(kb._tasks._idx) == 0
        # a retired uid reads as unsampled again
        assert kb.task_cpu_usage(["pod-500"])[0] == 0.0

    def test_retire_then_resample_is_clean(self):
        from poseidon_tpu.models.knowledge import KnowledgeBase, MachineSample

        kb = KnowledgeBase(queue_size=4)
        kb.add_machine_sample("m", MachineSample(cpu_idle=0.0, mem_free_frac=0.0))
        kb.retire_machine("m")
        kb.add_machine_sample("m", MachineSample(cpu_idle=1.0, mem_free_frac=1.0))
        assert kb.machine_cpu_idle(["m"])[0] == 1.0
        assert kb.machine_mem_free(["m"])[0] == 1.0
