"""Multi-round bridge simulation: pod lifecycle, reconcile, aging."""

import dataclasses

import numpy as np

from poseidon_tpu.bridge import SchedulerBridge
from poseidon_tpu.cluster import Machine, Task, TaskPhase


def _machines(n, slots=2):
    return [
        Machine(
            name=f"m{i}", rack=f"r{i % 2}", cpu_capacity=8,
            cpu_allocatable=8, memory_capacity_kb=1 << 22,
            memory_allocatable_kb=1 << 22, max_tasks=slots,
        )
        for i in range(n)
    ]


def _pods(n, phase=TaskPhase.PENDING):
    return [
        Task(uid=f"p{i}", job=f"j{i // 4}", cpu_request=0.5,
             memory_request_kb=1 << 12, phase=phase)
        for i in range(n)
    ]


class TestLifecycle:
    def test_pending_to_running_to_succeeded(self):
        bridge = SchedulerBridge(cost_model="trivial")
        bridge.observe_nodes(_machines(3))
        bridge.observe_pods(_pods(4))
        r1 = bridge.run_scheduler()
        assert r1.stats.pods_placed == 4
        assert set(r1.bindings) == {"p0", "p1", "p2", "p3"}

        # bindings confirmed -> Running; capacity is discounted
        for uid, m in r1.bindings.items():
            bridge.confirm_binding(uid, m)
        running = [
            dataclasses.replace(
                t, phase=TaskPhase.RUNNING, machine=r1.bindings[t.uid]
            )
            for t in _pods(4)
        ]
        bridge.observe_pods(running + _pods(8)[4:])
        r2 = bridge.run_scheduler()
        # only 6 - 4 = 2 slots remain on 3 machines x 2 slots
        assert r2.stats.pods_placed == 2
        assert r2.stats.pods_unscheduled == 2

        # succeeded pods free their slots
        done = [
            dataclasses.replace(t, phase=TaskPhase.SUCCEEDED)
            for t in running
        ]
        still_pending = [
            t for t in _pods(8)[4:]
            if t.uid not in r2.bindings
        ]
        for uid, m in r2.bindings.items():
            bridge.confirm_binding(uid, m)
        running2 = [
            dataclasses.replace(
                t, phase=TaskPhase.RUNNING, machine=r2.bindings[t.uid]
            )
            for t in _pods(8)[4:] if t.uid in r2.bindings
        ]
        bridge.observe_pods(done + running2 + still_pending)
        r3 = bridge.run_scheduler()
        assert r3.stats.pods_placed == 2  # freed slots absorb the rest
        assert r3.stats.pods_unscheduled == 0

    def test_restart_reconcile_adopts_running_pods(self):
        """The reference CHECK-crashes here (scheduler_bridge.cc:146-147):
        a fresh bridge observing already-Running pods must adopt them."""
        bridge = SchedulerBridge(cost_model="trivial")
        bridge.observe_nodes(_machines(2))
        running = [
            Task(uid="old0", cpu_request=0.5, phase=TaskPhase.RUNNING,
                 machine="m0"),
            Task(uid="old1", cpu_request=0.5, phase=TaskPhase.RUNNING,
                 machine="m0"),
        ]
        bridge.observe_pods(running + _pods(3))
        r = bridge.run_scheduler()
        # m0's 2 slots are taken by adopted pods: only m1's 2 remain
        assert r.stats.pods_placed == 2
        placed_on = set(r.bindings.values())
        assert placed_on == {"m1"}

    def test_node_removal_evicts(self):
        bridge = SchedulerBridge(cost_model="trivial")
        bridge.observe_nodes(_machines(2))
        bridge.observe_pods(_pods(2))
        r1 = bridge.run_scheduler()
        for uid, m in r1.bindings.items():
            bridge.confirm_binding(uid, m)
        # node m0 disappears
        bridge.observe_nodes(_machines(2)[1:])
        evicted = [
            uid for uid, t in bridge.tasks.items()
            if t.phase == TaskPhase.PENDING
        ]
        r2 = bridge.run_scheduler()
        assert r2.stats.evictions >= 0
        # every task ends up pending-or-placed on the surviving node
        for uid, t in bridge.tasks.items():
            assert t.machine in ("", "m1")

    def test_wait_rounds_grow_and_raise_unscheduled_cost(self):
        """ADVICE item 4: aging must actually increase the starvation
        pressure round over round."""
        bridge = SchedulerBridge(cost_model="quincy")
        bridge.observe_nodes(_machines(1, slots=1))
        bridge.observe_pods(_pods(3))
        r1 = bridge.run_scheduler()
        assert r1.stats.pods_unscheduled == 2
        w1 = [bridge.tasks[u].wait_rounds for u in r1.unscheduled]
        for uid, m in r1.bindings.items():
            bridge.confirm_binding(uid, m)
        r2 = bridge.run_scheduler()
        w2 = [bridge.tasks[u].wait_rounds for u in r2.unscheduled]
        assert all(b > a for a, b in zip(sorted(w1), sorted(w2)))
        # and the round cost reflects growing unscheduled penalties
        assert r2.stats.cost >= r1.stats.cost

    def test_warm_state_reused_across_rounds(self):
        # small_to_oracle off: warm on-HBM state only exists on the
        # dense path, which the production dispatcher skips for a
        # 4-machine/6-pod toy cluster
        bridge = SchedulerBridge(
            cost_model="quincy", small_to_oracle=False
        )
        bridge.observe_nodes(_machines(4))
        bridge.observe_pods(_pods(6))
        r1 = bridge.run_scheduler()
        assert bridge.warm_state is not None
        bridge.observe_pods(_pods(6))  # same pending set
        r2 = bridge.run_scheduler()
        assert r2.stats.cost == r1.stats.cost
