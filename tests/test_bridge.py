"""Multi-round bridge simulation: pod lifecycle, reconcile, aging."""

import dataclasses

from poseidon_tpu.bridge import SchedulerBridge
from poseidon_tpu.cluster import Machine, Task, TaskPhase


def _machines(n, slots=2):
    return [
        Machine(
            name=f"m{i}", rack=f"r{i % 2}", cpu_capacity=8,
            cpu_allocatable=8, memory_capacity_kb=1 << 22,
            memory_allocatable_kb=1 << 22, max_tasks=slots,
        )
        for i in range(n)
    ]


def _pods(n, phase=TaskPhase.PENDING):
    return [
        Task(uid=f"p{i}", job=f"j{i // 4}", cpu_request=0.5,
             memory_request_kb=1 << 12, phase=phase)
        for i in range(n)
    ]


class TestLifecycle:
    def test_pending_to_running_to_succeeded(self):
        bridge = SchedulerBridge(cost_model="trivial")
        bridge.observe_nodes(_machines(3))
        bridge.observe_pods(_pods(4))
        r1 = bridge.run_scheduler()
        assert r1.stats.pods_placed == 4
        assert set(r1.bindings) == {"p0", "p1", "p2", "p3"}

        # bindings confirmed -> Running; capacity is discounted
        for uid, m in r1.bindings.items():
            bridge.confirm_binding(uid, m)
        running = [
            dataclasses.replace(
                t, phase=TaskPhase.RUNNING, machine=r1.bindings[t.uid]
            )
            for t in _pods(4)
        ]
        bridge.observe_pods(running + _pods(8)[4:])
        r2 = bridge.run_scheduler()
        # only 6 - 4 = 2 slots remain on 3 machines x 2 slots
        assert r2.stats.pods_placed == 2
        assert r2.stats.pods_unscheduled == 2

        # succeeded pods free their slots
        done = [
            dataclasses.replace(t, phase=TaskPhase.SUCCEEDED)
            for t in running
        ]
        still_pending = [
            t for t in _pods(8)[4:]
            if t.uid not in r2.bindings
        ]
        for uid, m in r2.bindings.items():
            bridge.confirm_binding(uid, m)
        running2 = [
            dataclasses.replace(
                t, phase=TaskPhase.RUNNING, machine=r2.bindings[t.uid]
            )
            for t in _pods(8)[4:] if t.uid in r2.bindings
        ]
        bridge.observe_pods(done + running2 + still_pending)
        r3 = bridge.run_scheduler()
        assert r3.stats.pods_placed == 2  # freed slots absorb the rest
        assert r3.stats.pods_unscheduled == 0

    def test_restart_reconcile_adopts_running_pods(self):
        """The reference CHECK-crashes here (scheduler_bridge.cc:146-147):
        a fresh bridge observing already-Running pods must adopt them."""
        bridge = SchedulerBridge(cost_model="trivial")
        bridge.observe_nodes(_machines(2))
        running = [
            Task(uid="old0", cpu_request=0.5, phase=TaskPhase.RUNNING,
                 machine="m0"),
            Task(uid="old1", cpu_request=0.5, phase=TaskPhase.RUNNING,
                 machine="m0"),
        ]
        bridge.observe_pods(running + _pods(3))
        r = bridge.run_scheduler()
        # m0's 2 slots are taken by adopted pods: only m1's 2 remain
        assert r.stats.pods_placed == 2
        placed_on = set(r.bindings.values())
        assert placed_on == {"m1"}

    def test_node_removal_evicts(self):
        bridge = SchedulerBridge(cost_model="trivial")
        bridge.observe_nodes(_machines(2))
        bridge.observe_pods(_pods(2))
        r1 = bridge.run_scheduler()
        for uid, m in r1.bindings.items():
            bridge.confirm_binding(uid, m)
        # node m0 disappears
        bridge.observe_nodes(_machines(2)[1:])
        r2 = bridge.run_scheduler()
        assert r2.stats.evictions >= 0
        # every task ends up pending-or-placed on the surviving node
        for uid, t in bridge.tasks.items():
            assert t.machine in ("", "m1")

    def test_wait_rounds_grow_and_raise_unscheduled_cost(self):
        """ADVICE item 4: aging must actually increase the starvation
        pressure round over round."""
        bridge = SchedulerBridge(cost_model="quincy")
        bridge.observe_nodes(_machines(1, slots=1))
        bridge.observe_pods(_pods(3))
        r1 = bridge.run_scheduler()
        assert r1.stats.pods_unscheduled == 2
        w1 = [bridge.tasks[u].wait_rounds for u in r1.unscheduled]
        for uid, m in r1.bindings.items():
            bridge.confirm_binding(uid, m)
        r2 = bridge.run_scheduler()
        w2 = [bridge.tasks[u].wait_rounds for u in r2.unscheduled]
        assert all(b > a for a, b in zip(sorted(w1), sorted(w2)))
        # and the round cost reflects growing unscheduled penalties
        assert r2.stats.cost >= r1.stats.cost

    def test_warm_state_reused_across_rounds(self):
        # small_to_oracle off: warm on-HBM state only exists on the
        # dense path, which the production dispatcher skips for a
        # 4-machine/6-pod toy cluster
        bridge = SchedulerBridge(
            cost_model="quincy", small_to_oracle=False
        )
        bridge.observe_nodes(_machines(4))
        bridge.observe_pods(_pods(6))
        r1 = bridge.run_scheduler()
        assert bridge.warm_state is not None
        bridge.observe_pods(_pods(6))  # same pending set
        r2 = bridge.run_scheduler()
        assert r2.stats.cost == r1.stats.cost


class TestPipelinedEquivalence:
    """Pipelined rounds (begin/finish with overlapped observations)
    must produce the same bindings and certified-exact costs as serial
    rounds over the same observation stream."""

    def _obs_stream(self, rounds):
        """Deterministic per-round arrivals: (round -> new pods)."""
        out = []
        for r in range(rounds):
            out.append([
                Task(
                    uid=f"p{r}-{i}", job=f"j{r}-{i // 3}",
                    cpu_request=0.25,
                    memory_request_kb=1 << 12,
                    data_prefs={f"m{(r + i) % 5}": 60 + i},
                )
                for i in range(4 + (r % 3))
            ])
        return out

    def _snapshot(self, bridge, done):
        return [
            dataclasses.replace(t, phase=TaskPhase.SUCCEEDED)
            if t.uid in done else t
            for t in bridge.tasks.values()
        ]

    def _drive(self, pipelined, *, incremental=True, rounds=6):
        bridge = SchedulerBridge(
            cost_model="quincy", incremental_build=incremental
        )
        bridge.observe_nodes(_machines(5, slots=3))
        stream = self._obs_stream(rounds)
        results = []
        inflight = None
        for r in range(rounds):
            # pods placed two rounds ago finish now (available in both
            # modes: round r-2 has been finished by the time round r's
            # snapshot is taken, even pipelined)
            done = set(results[r - 2].bindings) if r >= 2 else set()
            bridge.observe_pods(
                self._snapshot(bridge, done) + stream[r]
            )
            if pipelined:
                if inflight is not None:
                    res = bridge.finish_round(inflight)
                    for uid, m in res.bindings.items():
                        bridge.confirm_binding(uid, m)
                    results.append(res)
                inflight = bridge.begin_round()
            else:
                res = bridge.run_scheduler()
                for uid, m in res.bindings.items():
                    bridge.confirm_binding(uid, m)
                results.append(res)
        if inflight is not None:
            res = bridge.finish_round(inflight)
            for uid, m in res.bindings.items():
                bridge.confirm_binding(uid, m)
            results.append(res)
        return results

    def test_same_bindings_and_costs(self):
        serial = self._drive(False)
        piped = self._drive(True)
        assert len(serial) == len(piped)
        for s, p in zip(serial, piped):
            assert s.bindings == p.bindings
            assert s.stats.cost == p.stats.cost
            assert sorted(s.unscheduled) == sorted(p.unscheduled)
            assert s.stats.pods_placed == p.stats.pods_placed

    def test_pipelined_equivalent_without_incremental_build(self):
        serial = self._drive(False, incremental=True)
        piped = self._drive(True, incremental=False)
        for s, p in zip(serial, piped):
            assert s.bindings == p.bindings
            assert s.stats.cost == p.stats.cost

    def test_double_begin_raises(self):
        bridge = SchedulerBridge(cost_model="trivial")
        bridge.observe_nodes(_machines(2))
        bridge.observe_pods(_pods(3))
        ir = bridge.begin_round()
        try:
            import pytest

            with pytest.raises(RuntimeError):
                bridge.begin_round()
        finally:
            bridge.finish_round(ir)

    def test_revoke_binding_reoffers_pod(self):
        """Optimistic confirm + failed POST: revoke flips the pod back
        to pending and the next round re-places it."""
        bridge = SchedulerBridge(cost_model="trivial")
        bridge.observe_nodes(_machines(2))
        bridge.observe_pods(_pods(2))
        r1 = bridge.run_scheduler()
        uid, machine = next(iter(r1.bindings.items()))
        bridge.confirm_binding(uid, machine)
        bridge.revoke_binding(uid)
        assert bridge.tasks[uid].phase == TaskPhase.PENDING
        r2 = bridge.run_scheduler()
        assert uid in r2.bindings

    def test_stale_placement_dropped_when_pod_moved_midflight(self):
        """A pod the overlap window's poll adopted as Running elsewhere
        (another scheduler, watch catch-up) must NOT come back in the
        in-flight round's bindings — that would clobber observed truth
        with a conflicting bind POST."""
        bridge = SchedulerBridge(cost_model="trivial")
        bridge.observe_nodes(_machines(3))
        bridge.observe_pods(_pods(2))
        ir = bridge.begin_round()
        # overlap window: the poll reports p0 already Running on m2
        moved = dataclasses.replace(
            _pods(2)[0], phase=TaskPhase.RUNNING, machine="m2"
        )
        bridge.observe_pods([moved, _pods(2)[1]])
        res = bridge.finish_round(ir)
        assert "p0" not in res.bindings
        assert "p0" not in res.unscheduled
        assert bridge.tasks["p0"].machine == "m2"
        # the still-pending pod's placement goes through normally
        assert "p1" in res.bindings

    def test_placement_on_vanished_machine_ages_as_unscheduled(self):
        """A placement whose target node disappeared during the overlap
        window is dropped AND accounted: the pod ages and shows up in
        unscheduled, like any other pod the round left behind."""
        bridge = SchedulerBridge(cost_model="trivial")
        bridge.observe_nodes(_machines(2, slots=4))
        bridge.observe_pods(_pods(3))
        ir = bridge.begin_round()
        # overlap window: every node vanishes (small cluster, no
        # shrink-hold at this size)
        bridge.observe_nodes([])
        res = bridge.finish_round(ir)
        assert res.bindings == {}
        assert sorted(res.unscheduled) == ["p0", "p1", "p2"]
        assert res.stats.pods_unscheduled == 3
        for uid in ("p0", "p1", "p2"):
            assert bridge.tasks[uid].wait_rounds == 1


class TestBindFailureAccounting:
    """Failed binding POSTs are counted in SchedulerStats and the pod
    is re-queued as unscheduled (aging preserved), not silently
    believed placed (the reference just logs, k8s_api_client.cc)."""

    def test_serial_failure_requeues_with_aging(self):
        bridge = SchedulerBridge(cost_model="trivial")
        bridge.observe_nodes(_machines(2))
        bridge.observe_pods(_pods(2))
        r1 = bridge.run_scheduler()
        uid, other = sorted(r1.bindings)
        # serial contract: the POST failed before any confirm
        bridge.binding_failed(uid)
        assert bridge.tasks[uid].phase == TaskPhase.PENDING
        assert bridge.tasks[uid].wait_rounds == 1
        # optimistic contract: confirmed Running first, then failed
        bridge.confirm_binding(other, r1.bindings[other])
        bridge.binding_failed(other)
        assert bridge.tasks[other].phase == TaskPhase.PENDING
        r2 = bridge.run_scheduler()
        assert r2.stats.bind_failures == 2
        # both pods were re-offered and land again
        assert set(r2.bindings) == {uid, other}
        # the counter is per-round: it resets after being reported
        bridge.observe_pods(
            [dataclasses.replace(t) for t in bridge.tasks.values()]
        )
        r3 = bridge.run_scheduler()
        assert r3.stats.bind_failures == 0
